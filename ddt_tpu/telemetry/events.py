"""Run-log events: schema-versioned JSONL records + in-memory ring buffer.

Every record is one JSON object per line with a fixed envelope
(`event`, `schema`, `t`, `seq`) plus the event type's required fields
(EVENT_FIELDS) and any optional extras. The schema is validated at EMIT
time (a malformed event is a bug at the producer, not something for the
report CLI to limp around) and again at READ time (report.read_events),
so a log that loads is a log every consumer can trust.

Writes are line-buffered appends of complete lines — a run killed mid-
round (the fault-injection story) loses at most its final partial line,
which read-side validation then skips with a warning rather than
discarding the run.
"""

from __future__ import annotations

import collections
import json
import time

SCHEMA_VERSION = 1

#: event type -> REQUIRED payload fields (extras are allowed and common:
#: e.g. `round` records carry `valid_<metric>` keys named by the run's
#: metric, and nullable fields like train_loss simply hold null).
EVENT_FIELDS: dict[str, set] = {
    # One per run, first record: what trained, on what, from where.
    "run_manifest": {"trainer", "backend", "loss", "n_trees", "max_depth",
                     "rows", "features"},
    # One per boosting round (the Driver.history record, as an event).
    "round": {"round", "ms_per_round"},
    # PhaseTimer.as_json() embedded verbatim under "phases".
    "phase_timings": {"phases"},
    # The early-stopping decision, when one fires.
    "early_stop": {"round", "best_round", "best_score", "metric"},
    # Fault/recovery events (today: checkpoint resume after a death).
    "fault": {"kind"},
    # Device-counter deltas over the run (telemetry.counters).
    "counters": {"jit_compiles", "h2d_bytes", "d2h_bytes",
                 "collective_bytes_est"},
    # Last record of a completed run.
    "run_end": {"completed_rounds", "wallclock_s"},
}

ENVELOPE_FIELDS = ("event", "schema", "t", "seq")


def validate_event(rec: dict) -> None:
    """Raise ValueError unless `rec` is a well-formed run-log record."""
    if not isinstance(rec, dict):
        raise ValueError(f"run-log record must be an object, got "
                         f"{type(rec).__name__}")
    missing = [k for k in ENVELOPE_FIELDS if k not in rec]
    if missing:
        raise ValueError(f"run-log record missing envelope fields {missing}")
    if not isinstance(rec["schema"], int) or isinstance(rec["schema"], bool):
        # A corrupt/hand-edited line must surface as the reader's clean
        # ValueError, not a TypeError from the comparison below.
        raise ValueError(
            f"run-log schema must be an integer, got {rec['schema']!r}")
    if rec["schema"] > SCHEMA_VERSION:
        raise ValueError(
            f"run-log schema {rec['schema']} is newer than this reader "
            f"(schema {SCHEMA_VERSION}); upgrade ddt_tpu to report on it")
    ev = rec["event"]
    if ev not in EVENT_FIELDS:
        raise ValueError(
            f"unknown run-log event {ev!r}; have {sorted(EVENT_FIELDS)}")
    missing = [k for k in EVENT_FIELDS[ev] if k not in rec]
    if missing:
        raise ValueError(f"{ev} record missing required fields {missing}")


class RunLog:
    """Append-only JSONL run log + bounded in-memory ring buffer.

    `path=None` keeps events in the ring only (tests, library callers).
    The file handle opens lazily on the first emit and is line-buffered;
    `close()` (or context-manager exit) releases it. Emission never
    touches the device — every field is host data the trainer already
    had in hand.
    """

    def __init__(self, path: str | None = None, ring_size: int = 4096):
        self.path = path
        self.ring: collections.deque = collections.deque(maxlen=ring_size)
        self._fh = None
        self._seq = 0

    @classmethod
    def coerce(cls, run_log) -> "RunLog | None":
        """None | path-str | RunLog -> RunLog | None (the api.train /
        fit_streaming argument convention)."""
        if run_log is None or isinstance(run_log, cls):
            return run_log
        return cls(str(run_log))

    def emit(self, event: str, **fields) -> dict:
        rec = {"event": event, "schema": SCHEMA_VERSION,
               "t": time.time(), "seq": self._seq, **fields}
        validate_event(rec)
        self._seq += 1
        self.ring.append(rec)
        if self.path is not None:
            if self._fh is None:
                self._fh = open(self.path, "a", buffering=1,
                                encoding="utf-8")
            self._fh.write(json.dumps(rec, sort_keys=False) + "\n")
        return rec

    def events(self, event: str | None = None) -> list[dict]:
        """Ring-buffer contents (oldest first), optionally one type."""
        return [r for r in self.ring if event is None or r["event"] == event]

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def emit_early_stop(run_log: "RunLog | None", stop_round: int, metric,
                    best_round: int, best_score) -> None:
    """The early_stop event, one emit site for the Driver's granular and
    fused loops and both streaming loops (rounds are 1-based here)."""
    if run_log is None:
        return
    run_log.emit("early_stop", round=stop_round, metric=metric,
                 best_round=best_round, best_score=best_score)


def finish_run_log(run_log: "RunLog | None", timer, counters_start,
                   completed_rounds: int, wallclock_s: float) -> None:
    """Run-log epilogue — phase_timings + counters + run_end — shared by
    Driver._finish_run and fit_streaming's _finish so the trainers'
    terminal records cannot drift. `timer` is a PhaseTimer or None;
    `counters_start` a telemetry.counters.snapshot() (or None). Closing
    path-owned logs is the trainers' ownership shims' job (Driver.fit /
    fit_streaming), which also covers the exception paths this helper
    never sees."""
    if run_log is None:
        return
    from ddt_tpu.telemetry import counters as tele_counters

    if timer is not None and timer.totals:
        run_log.emit("phase_timings", phases=timer.as_json())
    d = tele_counters.delta(counters_start or {})
    d["device_peak_bytes"] = tele_counters.device_peak_bytes()
    run_log.emit("counters", **d)
    run_log.emit("run_end", completed_rounds=completed_rounds,
                 wallclock_s=wallclock_s)


class RoundRecorder:
    """Per-round history record + run-log event + progress log line — the
    ONE home of the round-record shape, shared by the Driver's granular
    and fused loops (it replaced Driver._record_round) and mirrored by
    the streaming trainer's round events.

    Semantics preserved from the Driver: train loss at `log_every`
    cadence only (the loss thunk may cost a device sync; off-cadence
    records carry train_loss=None so the schema stays uniform), eval
    metric EVERY round — the per-round series (sklearn evals_result_)
    must not depend on the logging knob. ms_per_round is the caller's
    number: real per-round wallclock on the granular path, the block
    average on the fused path (per-round wallclock does not exist there
    — that is the point of fusing).
    """

    def __init__(self, history: list, run_log: RunLog | None,
                 log_every: int, n_rounds: int, metric_name: str | None,
                 logger):
        self.history = history
        self.run_log = run_log
        self.log_every = log_every
        self.n_rounds = n_rounds
        self.metric_name = metric_name
        self.log = logger

    @staticmethod
    def make_record(r: int, ms: float, train_loss,
                    metric_name=None, val_score=None) -> dict:
        """THE round-record dict shape ({round, train_loss, ms_per_round
        [, valid_<metric>]}) — also used by the streaming trainer's round
        events so the two emitters cannot drift."""
        rec = {"round": r + 1, "train_loss": train_loss,
               "ms_per_round": ms}
        if val_score is not None:
            rec[f"valid_{metric_name}"] = val_score
        return rec

    def record(self, r: int, ms: float, val_score, loss_fn) -> None:
        on_cadence = (r + 1) % self.log_every == 0 or r == self.n_rounds - 1
        if not on_cadence and val_score is None and self.run_log is None:
            return                       # nothing records this round
        loss = loss_fn() if on_cadence else None
        rec = self.make_record(r, ms, loss, self.metric_name, val_score)
        if on_cadence or val_score is not None:
            self.history.append(rec)
        if self.run_log is not None:
            self.run_log.emit("round", **rec)
        if on_cadence:
            self.log.info(
                "round %4d/%d  loss=%.6f  %.1f ms/round%s",
                r + 1, self.n_rounds, loss, ms,
                f"  valid_{self.metric_name}={val_score:.6f}"
                if val_score is not None else "",
            )
