"""Training operations plane: the live, read-only status daemon
(`cli train --status-port N`, ISSUE 20).

The serving tier became a scrapable system in ISSUE 17; this module is
the training half. A daemon thread serves three endpoints off a
`TrainStatus` aggregate the trainer updates at round boundaries:

- ``GET /healthz``       run_id, round i/N with phase, rolling-window
  rows/s and ETA, last checkpoint round + age, fault/retry counters,
  host peak RSS, per-device memory watermarks — the one-glance answer
  to "is this hours-long run still making progress?";
- ``GET /metrics``       Prometheus text exposition (the shared
  dialect, telemetry/exposition.py): every process counter as
  ``ddt_<name>_total`` (``ddt_train_rounds_total`` and the fault
  counters included), plus train-plane gauges
  (``ddt_train_rows_per_s``, ``ddt_train_round``/``_total_rounds``,
  ``ddt_train_checkpoint_age_seconds``) and the hist all-reduce byte
  estimate under its paper-facing name
  ``ddt_hist_allreduce_bytes_total``;
- ``GET /debug/rounds``  a ring of recent round records, mirroring the
  serve tier's ``/debug/requests``.

STRICTLY READ-ONLY: a scrape never resets a window, never emits a
run-log event, never mutates a counter (the `/stats?emit=1` contrast,
serve/metrics.py) — two scrapers and the trainer interleave freely and
every scraper sees the same monotone streams.

Zero-overhead-when-disabled contract (the disabled-telemetry contract,
docs/OBSERVABILITY.md, extended here): without `--status-port` the
trainer never imports this module, allocates no TrainStatus, and every
round-boundary hook is a single `is not None` test — exactly the
profiler-window gating pattern in driver.py.

Threading model (ddtlint thread-model pass covers this file): the
trainer thread writes via `begin_run`/`round_end`/`checkpoint_saved`;
HTTP handler threads read via `healthz`/`metrics_text`/`rounds_ring`.
Every access to mutable state holds `TrainStatus._lock`; the critical
sections are arithmetic-only — no I/O, no formatting — so a scrape can
never stall a training round and a round can never stall a scrape.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry.exposition import (EXPOSITION_CONTENT_TYPE, _num,
                                          render_counters)

log = logging.getLogger("ddt_tpu.statusd")

#: /debug/rounds ring capacity (mirrors serve's /debug/requests ring).
RING_ROUNDS = 256
#: rolling window (rounds) for the rows/s and ETA estimates — wide
#: enough to smooth per-round jitter, narrow enough to track a regime
#: change (e.g. a repartition) within a few checkpoints.
RATE_WINDOW = 32


class TrainStatus:
    """Shared run-progress aggregate between the trainer thread and the
    daemon's handler threads. All mutable state behind one lock; every
    method is O(window) arithmetic at most."""

    def __init__(self, ring: int = RING_ROUNDS,
                 window: int = RATE_WINDOW):
        self._lock = threading.Lock()
        self._run_id = None
        self._phase = "init"
        self._total_rounds = None
        self._rows = None
        self._rounds_done = 0
        self._round_ms = collections.deque(maxlen=window)
        self._ring = collections.deque(maxlen=ring)
        self._checkpoint_round = None
        self._checkpoint_t = None
        self._t_start = time.time()

    # -- trainer-side hooks (one call per boundary) ------------------- #
    def begin_run(self, run_id=None, total_rounds=None, rows=None,
                  phase: str = "train") -> None:
        """Stamp run identity once the trainer has derived it (a restart
        into the same status object resets the progress window)."""
        with self._lock:
            self._run_id = run_id
            self._total_rounds = total_rounds
            self._rows = rows
            self._phase = phase
            self._rounds_done = 0
            self._round_ms.clear()
            self._t_start = time.time()

    def set_phase(self, phase: str) -> None:
        with self._lock:
            self._phase = phase

    def round_end(self, rnd: int, ms: float, record=None) -> None:
        """One completed round: `rnd` 0-based, `ms` host wall time,
        `record` the round-record dict (RoundRecorder.make_record shape)
        for the /debug/rounds ring."""
        with self._lock:
            self._rounds_done = max(self._rounds_done, rnd + 1)
            self._round_ms.append(float(ms))
            if record is not None:
                self._ring.append(record)

    def checkpoint_saved(self, rnd: int) -> None:
        """`rnd` is the 1-based round count the checkpoint covers."""
        with self._lock:
            self._checkpoint_round = rnd
            self._checkpoint_t = time.time()

    # -- scrape-side (read-only) -------------------------------------- #
    def _progress_locked(self) -> dict:
        """Lock-held snapshot of the trainer-owned state; derived rates
        computed here so both /healthz and /metrics agree."""
        window_ms = sum(self._round_ms)
        n_window = len(self._round_ms)
        ms_per_round = window_ms / n_window if n_window else None
        rows_per_s = None
        if ms_per_round and self._rows:
            rows_per_s = self._rows / (ms_per_round / 1e3)
        eta_s = None
        if ms_per_round is not None and self._total_rounds is not None:
            left = max(0, self._total_rounds - self._rounds_done)
            eta_s = round(left * ms_per_round / 1e3, 3)
        return {
            "run_id": self._run_id,
            "phase": self._phase,
            "round": self._rounds_done,
            "total_rounds": self._total_rounds,
            "rows": self._rows,
            "ms_per_round": (round(ms_per_round, 3)
                             if ms_per_round is not None else None),
            "rows_per_s": (round(rows_per_s, 1)
                           if rows_per_s is not None else None),
            "eta_s": eta_s,
            "uptime_s": round(time.time() - self._t_start, 3),
            "last_checkpoint_round": self._checkpoint_round,
            "checkpoint_age_s": (
                round(time.time() - self._checkpoint_t, 3)
                if self._checkpoint_t is not None else None),
        }

    def healthz(self) -> dict:
        """The /healthz body. Process counters and memory watermarks are
        read OUTSIDE the lock — they are module-level monotone state
        with no ordering contract against the round window."""
        with self._lock:
            out = self._progress_locked()
        c = tele_counters.snapshot()
        out["counters"] = {
            "train_rounds": c.get("train_rounds", 0),
            "train_heartbeats": c.get("train_heartbeats", 0),
            "fault_retries": c.get("fault_retries", 0),
            "hist_oom_degrades": c.get("hist_oom_degrades", 0),
            "jit_compiles": c.get("jit_compiles", 0),
        }
        out["host_peak_rss_bytes"] = tele_counters.host_peak_rss_bytes()
        out["device_peak_bytes"] = tele_counters.device_peak_bytes()
        return out

    def metrics_text(self) -> str:
        """The /metrics body (shared exposition dialect). Counter series
        come straight from the process counter snapshot; the train-plane
        gauges from the progress window. Gauges without a value yet are
        OMITTED, not rendered as 0 — a 0 rate is a claim, not an
        absence (the serve-tier convention)."""
        with self._lock:
            p = self._progress_locked()
        c = tele_counters.snapshot()
        out = render_counters(c)
        # The hist all-reduce payload estimate under its paper-facing
        # name: an alias of collective_bytes_est, the counter the
        # histogram collectives already maintain.
        out.append("# TYPE ddt_hist_allreduce_bytes_total counter")
        out.append("ddt_hist_allreduce_bytes_total "
                   f"{_num(c.get('collective_bytes_est', 0))}")
        out.append("# TYPE ddt_train_round gauge")
        out.append(f"ddt_train_round {_num(p['round'])}")
        if p["total_rounds"] is not None:
            out.append("# TYPE ddt_train_total_rounds gauge")
            out.append(f"ddt_train_total_rounds {_num(p['total_rounds'])}")
        if p["rows_per_s"] is not None:
            out.append("# TYPE ddt_train_rows_per_s gauge")
            out.append(f"ddt_train_rows_per_s {_num(p['rows_per_s'])}")
        if p["checkpoint_age_s"] is not None:
            out.append("# TYPE ddt_train_checkpoint_age_seconds gauge")
            out.append("ddt_train_checkpoint_age_seconds "
                       f"{_num(p['checkpoint_age_s'])}")
        out.append("# TYPE ddt_host_peak_rss_bytes gauge")
        out.append("ddt_host_peak_rss_bytes "
                   f"{_num(tele_counters.host_peak_rss_bytes())}")
        dev = tele_counters.device_peak_bytes()
        if dev is not None:
            out.append("# TYPE ddt_device_peak_bytes gauge")
            out.append(f"ddt_device_peak_bytes {_num(dev)}")
        return "\n".join(out) + "\n"

    def rounds_ring(self) -> "list[dict]":
        """The /debug/rounds body: recent round records, oldest first."""
        with self._lock:
            return list(self._ring)


def _make_handler(status: TrainStatus):
    """Handler class closed over the status aggregate (the serve/http.py
    pattern — no globals, several daemons can coexist in one process,
    e.g. tests)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "ddt-statusd"

        def log_message(self, fmt, *args):   # stdlib logs to stderr
            log.debug("statusd: " + fmt, *args)

        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", EXPOSITION_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                self._send(200, status.healthz())
            elif path == "/metrics":
                # READ-ONLY by contract: formats snapshots, never emits
                # events, never resets a window (tests/test_statusd.py
                # pins the scrape-idempotence).
                self._send_text(200, status.metrics_text())
            elif path == "/debug/rounds":
                ring = status.rounds_ring()
                self._send(200, {"rounds": ring, "n": len(ring)})
            else:
                self._send(404, {"error": f"no route {path}",
                                 "routes": ["/healthz", "/metrics",
                                            "/debug/rounds"]})

    return Handler


class _Server(ThreadingHTTPServer):
    # Identical posture to the serve tier's adapter: handler threads are
    # daemons (a hung scraper cannot block trainer exit), modest listen
    # backlog (this is an ops endpoint, not a traffic port).
    daemon_threads = True
    request_queue_size = 128
    allow_reuse_address = True


class StatusDaemon:
    """Owns the HTTP server and its serving thread. The socket is bound
    in the CALLER's thread, so `port` is final (and an ephemeral port=0
    is resolved) before start() returns — the CLI prints it in the boot
    line the smoke harness reads."""

    def __init__(self, status: TrainStatus, host: str = "127.0.0.1",
                 port: int = 0):
        self.status = status
        self._server = _Server((host, port), _make_handler(status))
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._serve, name="ddt-statusd", daemon=True)

    def start(self) -> "StatusDaemon":
        self._thread.start()
        return self

    def _serve(self) -> None:
        try:
            self._server.serve_forever(poll_interval=0.05)
        finally:
            self._server.server_close()

    def close(self) -> None:
        """Idempotent shutdown; joins the serving thread."""
        self._server.shutdown()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


def start_statusd(status: TrainStatus, host: str = "127.0.0.1",
                  port: int = 0) -> StatusDaemon:
    """Bind + start the daemon thread; returns the handle (`.port` holds
    the bound port even for port=0)."""
    return StatusDaemon(status, host=host, port=port).start()
