"""Run-log diffing: `cli report diff A B` — "r06 got slower" -> why.

Given two run logs (same config or not — the diff says what changed,
the reader judges comparability), align by phase and counter and compute
per-phase wall-time and per-counter deltas, plus cost-analysis byte/flop
movement per phase. Excursions are flagged with benchwatch's band logic
degenerated to a single baseline: tools/benchwatch bands a metric at
median ± max(3·MAD, REL_FLOOR·|median|); with exactly one baseline run
the MAD term is zero, so the gate is the relative floor — an ADVERSE
move past REL_FLOOR (20%) of A's value flags, a favorable move never
does (one-sided, direction-aware, exactly the sentinel's semantics;
keep REL_FLOOR in sync with tools.benchwatch.REL_FLOOR).

The output turns "round 6 got slower" into "gain +34% (ms_total
120.1 -> 161.0), jit_compiles 12 -> 48, hist bytes-accessed x2.1".
Pure host-side post-processing (read -> summarize -> diff): no jax, no
device — two logs copied off a pod diff anywhere.
"""

from __future__ import annotations

#: mirror of tools.benchwatch.REL_FLOOR (the library must not import the
#: repo-layout tools/ package; the value is contract-commented there).
REL_FLOOR = 0.20

#: counter -> the direction whose GAIN is adverse. "lower" = an increase
#: flags; "higher" = a decrease flags; "neutral" = declared
#: workload-shape, never banded (request mix, fleet churn — a move in
#: either direction is a different workload, not a regression). EVERY
#: registered counter must appear here: a counter absent from this table
#: renders with a loud `direction=?` marker (and fails ddtlint's
#: counter-direction-missing rule) because an unknown direction silently
#: exempts the counter from the gate. Unknown numeric counters are still
#: reported, never flagged (benchwatch's unknown-metric rule: a guessed
#: direction can invert the gate).
COUNTER_DIRECTIONS: dict[str, str] = {
    "jit_compiles": "lower",
    "jit_compile_seconds": "lower",
    "h2d_bytes": "lower",
    "d2h_bytes": "lower",
    "collective_bytes_est": "lower",
    # Quantized gradients (ISSUE 14): the effective g/h HBM-stream
    # model — an f32 run diffed against an int8 run of the same shape
    # shows the 4x drop here; a quantized run regressing UP means the
    # integer path silently fell back to f32 streams.
    "grad_stream_bytes_est": "lower",
    "device_peak_bytes": "lower",
    "host_peak_rss_bytes": "lower",
    "compiled_ensemble_cache_hits": "higher",
    # Robustness counters: any uptick means the fault path fired — a
    # chaos run is EXPECTED to move these, but an ordinary A/B diff that
    # shows retries or OOM degradations appearing is a regression.
    "fault_retries": "lower",
    "hist_oom_degrades": "lower",
    # SLO breach transitions (serve/fleet.py, ISSUE 17): a serving A/B
    # whose B run starts burning its latency budget is a regression no
    # matter what the request mix looked like.
    "slo_breaches": "lower",
    # Drift alert transitions (serve/drift.py, ISSUE 19): a serving A/B
    # whose B run starts diverging from its training reference is a
    # regression regardless of the request mix — drift is a property of
    # the traffic-vs-model pairing, not of load.
    "drift_alerts": "lower",
    # Workload-shape counters: request mix and fleet churn track what
    # was ASKED of the system, not how well it did — deliberately
    # "neutral" so a bigger replay never reads as a regression.
    "serve_requests": "neutral",
    "serve_batches": "neutral",
    "serve_hot_swaps": "neutral",
    "serve_express": "neutral",
    "fleet_evictions": "neutral",
    "fleet_reloads": "neutral",
    "grad_quant_rounds": "neutral",
    # Training operations plane (ISSUE 20): rounds completed and
    # heartbeats emitted track the run's configured shape (n_trees,
    # checkpoint cadence), not its quality — a longer run must never
    # read as a regression, so both are "neutral".
    "train_rounds": "neutral",
    "train_heartbeats": "neutral",
}

#: flag floor for near-zero baselines (a 0 -> 3 ms phase is noise, a
#: 0 -> 300 ms phase is not).
ABS_FLOOR_MS = 50.0


def _cost_by_phase(summary: dict) -> dict:
    out: dict[str, dict] = {}
    for e in summary.get("cost_events") or []:
        rec = out.setdefault(e.get("phase", e.get("op")),
                             {"flops": 0.0, "bytes_accessed": 0.0})
        rec["flops"] += e.get("flops", 0.0) * e.get("calls", 1)
        rec["bytes_accessed"] += (e.get("bytes_accessed", 0.0)
                                  * e.get("calls", 1))
    return out


def _ratio(a, b):
    if not a:
        return None
    return round(b / a, 3)


def diff_summaries(sa: dict, sb: dict, threshold: float = REL_FLOOR,
                   abs_floor_ms: float = ABS_FLOOR_MS) -> dict:
    """Diff two report.summarize() dicts (A = baseline, B = current).
    Returns {"phases", "counters", "cost", "rounds", "flagged"} — the
    flagged list is the headline: human-ready attribution strings,
    worst first. `abs_floor_ms` suppresses phase flags on sub-noise
    absolute moves (drop it to 0 to band micro-runs)."""
    out: dict = {"phases": [], "counters": [], "cost": [],
                 "rounds": {}, "flagged": []}

    pa = {p["phase"]: p for p in sa.get("phases") or []}
    pb = {p["phase"]: p for p in sb.get("phases") or []}
    names = sorted(set(pa) | set(pb),
                   key=lambda n: -(pa.get(n, pb.get(n))["ms_total"]))
    for name in names:
        a, b = pa.get(name), pb.get(name)
        rec = {
            "phase": name,
            "a_ms": a["ms_total"] if a else None,
            "b_ms": b["ms_total"] if b else None,
            "a_ms_per_call": a["ms_per_call"] if a else None,
            "b_ms_per_call": b["ms_per_call"] if b else None,
            "a_calls": a["calls"] if a else 0,
            "b_calls": b["calls"] if b else 0,
            "flag": None,
        }
        if a and b:
            delta = b["ms_total"] - a["ms_total"]
            rec["delta_ms"] = round(delta, 2)
            rec["ratio"] = _ratio(a["ms_total"], b["ms_total"])
            if delta > max(threshold * a["ms_total"], abs_floor_ms):
                rec["flag"] = "slower"
                pct = 100.0 * delta / a["ms_total"]
                out["flagged"].append(
                    f"{name} +{pct:.0f}% ({a['ms_total']:.1f} -> "
                    f"{b['ms_total']:.1f} ms total, "
                    f"{a['ms_per_call']:.2f} -> {b['ms_per_call']:.2f} "
                    "ms/call)")
        elif b and not a:
            rec["flag"] = "new"
        elif a and not b:
            rec["flag"] = "gone"
        out["phases"].append(rec)

    ca = sa.get("counters") or {}
    cb = sb.get("counters") or {}
    for key in sorted(set(ca) | set(cb)):
        va, vb = ca.get(key), cb.get(key)
        if not all(isinstance(v, (int, float)) and not isinstance(v, bool)
                   for v in (va, vb) if v is not None):
            continue
        direction = COUNTER_DIRECTIONS.get(key)
        # "?" marks a counter missing from COUNTER_DIRECTIONS — loud in
        # both the JSON record and the text rendering so the gap is
        # visible at the point of use, not just in the lint gate.
        rec = {"counter": key, "a": va, "b": vb, "flag": None,
               "direction": direction or "?"}
        # A zero/absent baseline has no band to measure against — the
        # benchwatch rule (metrics with no usable history are reported,
        # never guessed at): a single-chip baseline's
        # collective_bytes_est=0 vs a pod run's N must not fail --check.
        # "neutral" (and unknown) directions are reported, never banded.
        if va and vb is not None and direction in ("lower", "higher"):
            delta = vb - va
            adverse = delta if direction == "lower" else -delta
            if adverse > threshold * abs(va) and adverse > 0:
                rec["flag"] = "worse"
                out["flagged"].append(f"{key} {va:g} -> {vb:g}")
        out["counters"].append(rec)

    costa, costb = _cost_by_phase(sa), _cost_by_phase(sb)
    for name in sorted(set(costa) | set(costb)):
        a = costa.get(name, {"flops": 0.0, "bytes_accessed": 0.0})
        b = costb.get(name, {"flops": 0.0, "bytes_accessed": 0.0})
        rec = {"phase": name,
               "a_bytes": a["bytes_accessed"], "b_bytes": b["bytes_accessed"],
               "bytes_ratio": _ratio(a["bytes_accessed"],
                                     b["bytes_accessed"]),
               "a_flops": a["flops"], "b_flops": b["flops"],
               "flops_ratio": _ratio(a["flops"], b["flops"]),
               "flag": None}
        br = rec["bytes_ratio"]
        if br is not None and br > 1.0 + threshold:
            rec["flag"] = "bytes-bloat"
            out["flagged"].append(f"{name} bytes-accessed x{br:.1f}")
        out["cost"].append(rec)

    wa, wb = sa.get("wallclock_s"), sb.get("wallclock_s")
    out["rounds"] = {
        "a_rounds": sa.get("completed_rounds"),
        "b_rounds": sb.get("completed_rounds"),
        "a_wallclock_s": wa, "b_wallclock_s": wb,
        "wallclock_ratio": _ratio(wa, wb) if wa and wb else None,
    }
    return out


def render_diff(d: dict, label_a: str = "A", label_b: str = "B") -> str:
    """Terminal rendering of diff_summaries()."""
    out = [f"run diff: A={label_a}  B={label_b}"]
    r = d["rounds"]
    if r.get("a_wallclock_s") is not None \
            and r.get("b_wallclock_s") is not None:
        out.append(
            f"wallclock: {r['a_wallclock_s']:.2f}s -> "
            f"{r['b_wallclock_s']:.2f}s"
            + (f"  (x{r['wallclock_ratio']:.2f})"
               if r.get("wallclock_ratio") else "")
            + f"  rounds {r['a_rounds']} -> {r['b_rounds']}")
    if d["flagged"]:
        out.append("flagged excursions (adverse move past the "
                   f"{int(100 * REL_FLOOR)}% band):")
        for f in d["flagged"]:
            out.append(f"  ! {f}")
    else:
        out.append("no adverse excursions past the band")
    if d["phases"]:
        out.append("phases (ms total A -> B):")
        for p in d["phases"]:
            a = f"{p['a_ms']:.1f}" if p["a_ms"] is not None else "-"
            b = f"{p['b_ms']:.1f}" if p["b_ms"] is not None else "-"
            extra = f"  x{p['ratio']:.2f}" if p.get("ratio") else ""
            flag = f"  [{p['flag']}]" if p["flag"] else ""
            out.append(f"  {p['phase']:<14} {a:>10} -> {b:>10}"
                       f"{extra}{flag}")
    changed = [c for c in d["counters"]
               if c["a"] != c["b"] or c["flag"]]
    if changed:
        out.append("counters (A -> B):")
        for c in changed:
            flag = "  [worse]" if c["flag"] else ""
            # Loud marker: this counter has no registered direction, so
            # it can NEVER flag — the gate is silently blind to it until
            # COUNTER_DIRECTIONS (and the lint contract) learn it.
            unknown = ("  direction=? (unregistered counter — add it to "
                       "COUNTER_DIRECTIONS)"
                       if c.get("direction") == "?" else "")
            out.append(f"  {c['counter']:<28} {c['a']} -> {c['b']}"
                       f"{flag}{unknown}")
    bloat = [c for c in d["cost"] if c["bytes_ratio"] not in (None, 1.0)]
    if bloat:
        out.append("cost-analysis bytes accessed per phase (A -> B):")
        for c in bloat:
            flag = "  [bytes-bloat]" if c["flag"] else ""
            out.append(
                f"  {c['phase']:<14} {c['a_bytes']:.3g} -> "
                f"{c['b_bytes']:.3g}  x{c['bytes_ratio']:.2f}{flag}")
    return "\n".join(out)
