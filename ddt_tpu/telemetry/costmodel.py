"""Device-truth cost observatory: XLA's own cost model, wired to the run log.

The telemetry stack answers *where* time goes (host wall-clock per phase,
per-partition skew); this module answers *why*: a slow `ddt:hist` round
could be HBM-bandwidth-bound, recompile-thrashed, or padding-bloated, and
a host clock alone cannot distinguish them. XLA's compiled-executable
`cost_analysis()` (FLOPs, bytes accessed) and `memory_analysis()`
(argument/output/temp HBM bytes) are the ground truth for what a compiled
program actually costs — GPU tree-boosting work (arXiv:1706.08359) shows
histogram building lives or dies on achieved memory bandwidth, and the
TPU compilation literature (arXiv:1810.09868) treats XLA's analyses as
the authoritative cost model. This module pulls those numbers at compile
time, joins them against the measured phase wall-times, and renders a
roofline verdict per phase: compute-bound / HBM-bound / recompile / host.

Three pieces:

- **costed(op, phase)** — a transparent wrapper for jit entry points
  (`CostedFn`). When a collector is ACTIVE (a run log is attached), the
  first top-level call with a new argument signature AOT-lowers and
  compiles the same program once more purely for analysis
  (`fn.lower(*args).compile()`), records FLOPs / bytes-accessed /
  HBM-byte breakdown, and counts subsequent calls per signature. When no
  collector is active the wrapper is ONE module-global read per call —
  the hot path never lowers, never compiles, never syncs (guard-tested
  with the rest of the disabled-telemetry invariant). Calls made while
  tracing (the op riding inside a larger jit/shard_map program) are
  skipped: the enclosing program's own entry point carries the cost.
  The analysis compile is paid once per (op, signature) per telemetry
  run; with the persistent XLA compile cache enabled it degrades to a
  disk read.
- **Collector / activate() / flush_into()** — per-run capture state. The
  trainers activate on telemetry runs, and `finish_run_log` flushes one
  schema-v3 `cost_analysis` event per (op, signature) — per-call FLOPs
  and bytes plus the observed call count — into the run log's epilogue.
- **roofline_table()** — the read side: join cost events against the
  run's `phase_timings` and the compile-time counters, compute achieved
  GFLOP/s and GB/s against per-platform peak ceilings, and attach a
  bound-by verdict. Pure host math, no jax — a log reports anywhere
  (the report CLI contract).

Verdict semantics (documented, deliberately coarse): a phase whose
device utilization is visible (>= HOST_BOUND_UTIL on either axis) is
"compute" or "hbm" by which roofline axis it sits closer to; a phase the
device barely noticed is "host" (dispatch / host work dominated) —
upgraded to "recompile" when the run's cumulative backend-compile
wall-time (`counters.jit_compile_seconds`) claims more than
RECOMPILE_WALL_SHARE of the run, since compiles bill their wall time to
whichever phase first hit the fresh shape.
"""

from __future__ import annotations

try:
    import jax
except ImportError:               # jax-less host: capture never activates
    jax = None

#: Nominal per-platform roofline ceilings: peak GFLOP/s and HBM GB/s.
#: These are deployment constants, not measurements — the v5e figures are
#: the spec sheet (bf16 MXU peak, HBM2E bandwidth per chip); the cpu/gpu
#: rows are order-of-magnitude defaults so off-TPU logs still render a
#: table. Utilization fractions, not absolute verdicts, are the signal —
#: refine per fleet in one place here.
#: `coll_gbs` is the interconnect ceiling the comms roofline row divides
#: by: order-of-magnitude per-chip collective bandwidth (v5e ICI; DCN is
#: lower still — the verdict is about whether the wire binds at all, not
#: which wire).
PEAK_CEILINGS: dict[str, dict] = {
    "tpu": {"gflops": 197_000.0, "gbs": 819.0, "coll_gbs": 90.0},
    "gpu": {"gflops": 19_500.0, "gbs": 900.0, "coll_gbs": 300.0},
    "cpu": {"gflops": 150.0, "gbs": 30.0, "coll_gbs": 10.0},
}

#: Below this utilization on BOTH roofline axes the device was mostly
#: idle during the phase — the phase is host/dispatch-bound.
HOST_BOUND_UTIL = 0.02
#: Run-level compile share above which idle-device phases are attributed
#: to recompilation rather than plain host work.
RECOMPILE_WALL_SHARE = 0.25

# ------------------------------------------------------------------ #
# collection
# ------------------------------------------------------------------ #

_active: "Collector | None" = None


class Collector:
    """Capture state for ONE telemetry run: (op, signature) -> record."""

    def __init__(self):
        self.records: dict[tuple, dict] = {}

    def on_call(self, op: str, phase: str, fn, args, kwargs) -> None:
        if not _host_context(args):
            return                      # riding inside a traced program
        key = (op, _signature(args, kwargs))
        rec = self.records.get(key)
        if rec is not None:
            rec["calls"] += 1
            return
        rec = {"op": op, "phase": phase, "calls": 1,
               "signature": _sig_str(key[1])}
        rec.update(_capture(fn, args, kwargs))
        self.records[key] = rec

    def events(self) -> list[dict]:
        """Flushable cost_analysis payloads, op-sorted for stable logs."""
        return [dict(r) for r in sorted(
            self.records.values(),
            key=lambda r: (r["op"], r["signature"]))]


def activate() -> "Collector | None":
    """Install a fresh collector (telemetry-run prologue). Returns None
    on a jax-less host — every costed entry point is device code, so
    there is nothing to collect."""
    global _active
    if jax is None:
        return None
    _active = Collector()
    return _active


def deactivate(collector: "Collector | None") -> None:
    """Remove `collector` if it is still the active one (trainer
    epilogues/ownership shims call this in `finally`, so a crashed run
    cannot leak capture work into the next — possibly telemetry-less —
    run in the same process)."""
    global _active
    if collector is not None and _active is collector:
        _active = None


def flush_into(run_log, collector: "Collector | None") -> None:
    """Emit one `cost_analysis` event per captured (op, signature) —
    the finish_run_log epilogue's cost section."""
    if run_log is None or collector is None:
        return
    for rec in collector.events():
        run_log.emit("cost_analysis", **rec)


def _host_context(args) -> bool:
    """True when we are NOT inside a jax trace (lowering from within a
    trace is invalid, and an op called under an enclosing jit bills its
    cost to that program's entry point, not its own)."""
    try:
        if not jax.core.trace_state_clean():
            return False
    except AttributeError:      # older/newer jax: fall back to arg probe
        pass
    return not any(isinstance(a, jax.core.Tracer) for a in args)


def _sig_of(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("a", tuple(x.shape), str(x.dtype))
    if isinstance(x, (bool, int, float, str, type(None))):
        return ("v", x)
    return ("o", type(x).__name__)


def _signature(args, kwargs) -> tuple:
    return (tuple(_sig_of(a) for a in args),
            tuple(sorted((k, _sig_of(v)) for k, v in kwargs.items())))


def _sig_str(sig: tuple) -> str:
    """Human/JSON form of a signature: shapes only, the part a reader
    can act on ("hist at [1000000, 28] uint8 ...")."""
    parts = []
    for s in sig[0]:
        parts.append(f"{list(s[1])}:{s[2]}" if s[0] == "a" else str(s[1]))
    for k, s in sig[1]:
        parts.append(
            f"{k}={list(s[1])}:{s[2]}" if s[0] == "a" else f"{k}={s[1]}")
    return "(" + ", ".join(parts) + ")"


def _capture(fn, args, kwargs) -> dict:
    """AOT-lower + compile `fn` at `args` and extract XLA's cost and
    memory analyses. One extra backend compile per (op, signature),
    paid only on telemetry runs; failures degrade to a zeroed record
    carrying the error — cost capture must never fail a training run."""
    from ddt_tpu.telemetry import counters as tele_counters

    try:
        # The analysis compile must not bill itself to the recompile
        # counters it exists to explain (counters.suppress_compile_
        # counting); its wall time inside the enclosing phase span is a
        # one-time cost documented in docs/OBSERVABILITY.md.
        with tele_counters.suppress_compile_counting():
            compiled = fn.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        rec = {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            "platform": str(jax.default_backend()),
        }
        try:
            ma = compiled.memory_analysis()
        except (NotImplementedError, RuntimeError, AttributeError):
            ma = None
        if ma is not None:
            for field, key in (("argument_size_in_bytes", "arg_bytes"),
                               ("output_size_in_bytes", "output_bytes"),
                               ("temp_size_in_bytes", "temp_bytes")):
                v = getattr(ma, field, None)
                if v is not None:
                    rec[key] = int(v)
        return rec
    except (TypeError, ValueError, RuntimeError, NotImplementedError,
            AttributeError, KeyError, OSError) as e:
        return {"flops": 0.0, "bytes_accessed": 0.0,
                "platform": str(jax.default_backend()) if jax else None,
                "error": f"{type(e).__name__}: {e}"[:300]}


class CostedFn:
    """Transparent cost-capturing wrapper around a jit entry point.

    Call semantics are untouched — the wrapped function runs exactly as
    before; attribute access (``.lower``, ``.clear_cache``, ...) passes
    through to the underlying jit object. The ONLY added work per call
    is one module-global read when no collector is active, or a dict
    lookup + integer add when one is."""

    __slots__ = ("_fn", "op", "phase", "__wrapped__")

    def __init__(self, fn, op: str, phase: str):
        self._fn = fn
        self.op = op
        self.phase = phase
        self.__wrapped__ = fn

    def __call__(self, *args, **kwargs):
        col = _active
        if col is not None:
            col.on_call(self.op, self.phase, self._fn, args, kwargs)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_fn"), name)

    def __repr__(self):
        return f"CostedFn({self.op!r}, {self._fn!r})"


def costed(op: str, phase: str | None = None):
    """Decorator/wrapper factory: ``costed("hist", phase="hist")(jitted)``.
    `op` names the program in cost_analysis events; `phase` (default:
    `op`) is the run-log phase_timings name the roofline join keys on."""
    def wrap(fn):
        return CostedFn(fn, op, phase if phase is not None else op)

    return wrap


def analyze(fn, *args, **kwargs) -> dict:
    """One-shot explicit cost analysis of `fn` at `args` — the bench
    harness's roofline stamp. `fn` may be a jit object (has .lower) or a
    plain traceable callable (jitted here). Returns the _capture record
    ({flops, bytes_accessed, platform, ...})."""
    if jax is None:
        return {"flops": 0.0, "bytes_accessed": 0.0, "platform": None,
                "error": "jax unavailable"}
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    return _capture(fn, args, kwargs)


# ------------------------------------------------------------------ #
# the read side: roofline join (pure host math — no jax)
# ------------------------------------------------------------------ #

def peaks_for(platform: str | None) -> dict:
    return PEAK_CEILINGS.get(platform or "", PEAK_CEILINGS["cpu"])


def roofline_table(phases: list[dict], cost_events: list[dict],
                   counters: dict | None = None,
                   wallclock_s: float | None = None) -> list[dict]:
    """Join `phase_timings` records against `cost_analysis` events into
    roofline rows: achieved GFLOP/s and GB/s per phase vs the platform's
    peak ceilings, with a bound-by verdict.

    `phases` is PhaseTimer.as_json() (the run log's phase_timings);
    `cost_events` the run's cost_analysis records. Phases without cost
    data still get a row (verdict "host" — no device program was
    registered under that name; e.g. the streamed gain phase, which is
    NumPy split selection by design). The fused path's `grow_block`
    dispatch is async, so its row folds in the `fetch_tree` barrier that
    carries the block's device wallclock (and fetch_tree's own row is
    dropped)."""
    ms_by_phase = {p["phase"]: p for p in phases}
    ev_by_phase: dict[str, list] = {}
    platform = None
    for e in cost_events:
        ev_by_phase.setdefault(e.get("phase", e.get("op")), []).append(e)
        platform = platform or e.get("platform")
    peaks = peaks_for(platform)
    compile_s = float((counters or {}).get("jit_compile_seconds") or 0.0)
    compile_share = (compile_s / wallclock_s
                     if wallclock_s and wallclock_s > 0 else 0.0)

    rows = []
    for p in phases:
        name = p["phase"]
        if name == "fetch_tree" and "grow_block" in ms_by_phase:
            continue                      # folded into the grow_block row
        wall_ms = p["ms_total"]
        if name == "grow_block" and "fetch_tree" in ms_by_phase:
            wall_ms += ms_by_phase["fetch_tree"]["ms_total"]
        evs = ev_by_phase.get(name, [])
        flops = sum(e.get("flops", 0.0) * e.get("calls", 1) for e in evs)
        byts = sum(e.get("bytes_accessed", 0.0) * e.get("calls", 1)
                   for e in evs)
        row = {"phase": name, "ms": round(wall_ms, 1),
               "calls": p.get("calls"), "n_programs": len(evs)}
        if not evs or wall_ms <= 0 or (flops <= 0 and byts <= 0):
            row.update(gflops=None, gbs=None, flops_util=None,
                       hbm_util=None,
                       verdict="recompile"
                       if evs and compile_share > RECOMPILE_WALL_SHARE
                       else "host")
            rows.append(row)
            continue
        wall_s = wall_ms / 1e3
        gflops = flops / wall_s / 1e9
        gbs = byts / wall_s / 1e9
        uc = gflops / peaks["gflops"]
        ub = gbs / peaks["gbs"]
        if max(uc, ub) < HOST_BOUND_UTIL:
            verdict = ("recompile" if compile_share > RECOMPILE_WALL_SHARE
                       else "host")
        elif ub >= uc:
            verdict = "hbm"
        else:
            verdict = "compute"
        row.update(gflops=round(gflops, 2), gbs=round(gbs, 2),
                   flops_util=round(uc, 4), hbm_util=round(ub, 4),
                   verdict=verdict)
        rows.append(row)
    # Comms roofline row (ISSUE 10, docs/PERF.md "Histogram comms"): the
    # run's EFFECTIVE collective payload (counters.collective_bytes_est —
    # post-compression, post-scatter, subtraction-halved) against the
    # interconnect ceiling, attributed to the phase whose programs carry
    # the collective. Verdict "comms" when the wire's utilization rivals
    # or beats the carrying phase's HBM leg (the wire binds); else
    # "overlapped" — the latency is hidden behind compute, which is the
    # state the comms-lean split finding exists to reach.
    coll_bytes = float((counters or {}).get("collective_bytes_est") or 0.0)
    if coll_bytes > 0:
        carrier = next((r for r in rows
                        if r["phase"] in ("grow_block", "grow", "hist")
                        and r["ms"] > 0), None)
        if carrier is not None:
            gbs = coll_bytes / (carrier["ms"] / 1e3) / 1e9
            cu = gbs / peaks.get("coll_gbs", peaks["gbs"])
            verdict = ("comms"
                       if cu >= HOST_BOUND_UTIL
                       and cu >= (carrier.get("hbm_util") or 0.0)
                       else "overlapped")
            rows.append({
                "phase": "comms", "ms": carrier["ms"], "calls": None,
                "n_programs": 0, "gflops": None, "gbs": round(gbs, 2),
                "flops_util": None, "hbm_util": None,
                "coll_util": round(cu, 4), "verdict": verdict})
    rows.sort(key=lambda r: -r["ms"])
    return rows
