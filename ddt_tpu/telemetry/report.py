"""Render a run summary from a JSONL run log (`ddt_tpu.cli report`).

Pure host-side post-processing: read_events -> summarize -> render. The
summary is a plain dict (the CLI's --json form); render() formats it for
a terminal. No jax, no device, no repo state — a run log copied off a
pod host reports anywhere.
"""

from __future__ import annotations

import json

from ddt_tpu.telemetry.events import validate_event


def read_events(path: str) -> list[dict]:
    """Parse + validate a JSONL run log. Raises ValueError naming the
    line on a malformed record; a TRAILING partial line (the run was
    killed mid-write) is tolerated and dropped — everything above it is
    intact by the append-only write contract."""
    events: list[dict] = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines, start=1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if i == len(lines):
                # Torn FINAL line (the run was killed mid-write): the
                # crash-consistency contract (events.py) says everything
                # above it is intact, so drop just the tail. Records stay
                # schema-pure — no out-of-schema marker keys.
                break
            raise ValueError(f"{path}:{i}: not JSON: {e}") from None
        try:
            validate_event(rec)
        except ValueError as e:
            raise ValueError(f"{path}:{i}: {e}") from None
        events.append(rec)
    if not events:
        raise ValueError(f"{path}: no run-log events")
    return events


def _metric_key(rec: dict) -> str | None:
    for k in rec:
        if k.startswith("valid_"):
            return k
    return None


def summarize(events: list[dict], slowest: int = 5) -> dict:
    """Aggregate a run log into the report dict (see render for the
    shape as prose)."""
    # Append-mode logs can hold several run segments (a preemptible
    # restart re-runs the command into the same file; each fit emits its
    # own manifest). Report the LAST segment — the run that completed —
    # and surface the segment count so earlier attempts stay visible.
    n_runs = sum(1 for e in events if e["event"] == "run_manifest")
    for i in range(len(events) - 1, -1, -1):
        if events[i]["event"] == "run_manifest":
            events = events[i:]
            break

    manifest = next((e for e in events if e["event"] == "run_manifest"), {})
    rounds = [e for e in events if e["event"] == "round"]
    phase_ev = [e for e in events if e["event"] == "phase_timings"]
    counter_ev = [e for e in events if e["event"] == "counters"]
    run_end = next((e for e in events if e["event"] == "run_end"), None)

    metric_curve = []
    metric = None
    for r in rounds:
        mk = _metric_key(r)
        if mk is not None:
            metric = mk[len("valid_"):]
            metric_curve.append({"round": r["round"], "score": r[mk]})
    losses = [{"round": r["round"], "train_loss": r["train_loss"]}
              for r in rounds if r.get("train_loss") is not None]

    timed = sorted((r for r in rounds if r.get("ms_per_round") is not None),
                   key=lambda r: -r["ms_per_round"])
    summary = {
        "manifest": {k: v for k, v in manifest.items()
                     if k not in ("event", "schema", "t", "seq")},
        "n_runs_in_log": n_runs,
        "n_round_records": len(rounds),
        "completed_rounds": run_end["completed_rounds"] if run_end else None,
        "wallclock_s": run_end["wallclock_s"] if run_end else None,
        "metric": metric,
        "metric_curve": metric_curve,
        "train_loss_curve": losses,
        "phases": phase_ev[-1]["phases"] if phase_ev else [],
        "counters": (
            {k: v for k, v in counter_ev[-1].items()
             if k not in ("event", "schema", "t", "seq")}
            if counter_ev else {}),
        "slowest_rounds": [
            {"round": r["round"], "ms_per_round": r["ms_per_round"]}
            for r in timed[:slowest]],
        "early_stop": next(
            ({k: e[k] for k in ("round", "best_round", "best_score",
                                "metric")}
             for e in events if e["event"] == "early_stop"), None),
        "faults": [
            {k: v for k, v in e.items()
             if k not in ("event", "schema", "t", "seq")}
            for e in events if e["event"] == "fault"],
    }
    return summary


def _fmt_bytes(n) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n}"


def render(summary: dict) -> str:
    """Terminal rendering of summarize()'s dict."""
    out: list[str] = []
    m = summary["manifest"]
    head = " ".join(
        f"{k}={m[k]}" for k in ("trainer", "backend", "loss", "n_trees",
                                "max_depth", "rows", "features") if k in m)
    out.append(f"run: {head or '(no manifest)'}")
    if summary.get("n_runs_in_log", 1) > 1:
        out.append(f"note: log holds {summary['n_runs_in_log']} run "
                   "segments; reporting the last")
    done = summary["completed_rounds"]
    wc = summary["wallclock_s"]
    out.append(
        f"rounds: {summary['n_round_records']} recorded"
        + (f", {done} completed" if done is not None else "")
        + (f", {wc:.2f}s wallclock" if wc is not None else ""))

    if summary["early_stop"]:
        es = summary["early_stop"]
        out.append(
            f"early stop at round {es['round']} "
            f"(best {es['metric']}={es['best_score']:.6f} "
            f"at round {es['best_round']})")
    for f in summary["faults"]:
        detail = {k: v for k, v in f.items() if k != "kind"}
        out.append(f"fault/recovery: {f['kind']} {detail or ''}".rstrip())

    if summary["phases"]:
        out.append("phases (host wallclock):")
        for p in summary["phases"]:
            out.append(
                f"  {p['phase']:<14} {p['ms_total']:>9.1f} ms total  "
                f"{p['ms_per_call']:>8.2f} ms/call  x{p['calls']:<6} "
                f"{100 * p['share']:5.1f}%")

    curve = summary["metric_curve"]
    if curve:
        name = summary["metric"]
        first, last = curve[0], curve[-1]
        # Direction from the ONE metrics table (utils.metrics) — a copy
        # here would silently label the worst round "best" for any
        # metric added there later. Unknown names (a log from a newer
        # build) default to lower-is-better, the loss convention.
        from ddt_tpu.utils.metrics import GREATER_IS_BETTER

        best = max(curve, key=lambda c: c["score"]) \
            if GREATER_IS_BETTER.get(name, False) \
            else min(curve, key=lambda c: c["score"])
        out.append(
            f"valid_{name}: first={first['score']:.6f} "
            f"(round {first['round']})  best={best['score']:.6f} "
            f"(round {best['round']})  last={last['score']:.6f} "
            f"(round {last['round']})  [{len(curve)} rounds]")
    losses = summary["train_loss_curve"]
    if losses:
        out.append(
            f"train_loss: first={losses[0]['train_loss']:.6f} "
            f"(round {losses[0]['round']})  "
            f"last={losses[-1]['train_loss']:.6f} "
            f"(round {losses[-1]['round']})")

    c = summary["counters"]
    if c:
        out.append(
            "counters: "
            f"jit_compiles={c.get('jit_compiles')}  "
            f"h2d={_fmt_bytes(c.get('h2d_bytes'))}  "
            f"d2h={_fmt_bytes(c.get('d2h_bytes'))}  "
            f"collective≈{_fmt_bytes(c.get('collective_bytes_est'))}  "
            f"device_peak={_fmt_bytes(c.get('device_peak_bytes'))}")
        # Scoring-cache effectiveness (absent in pre-overhaul logs).
        hits = c.get("compiled_ensemble_cache_hits")
        if hits is not None:
            out.append(f"predict: compiled_ensemble_cache_hits={hits}")

    if summary["slowest_rounds"]:
        slow = ", ".join(f"#{r['round']} ({r['ms_per_round']:.1f} ms)"
                         for r in summary["slowest_rounds"])
        out.append(f"slowest rounds: {slow}")
    return "\n".join(out)
