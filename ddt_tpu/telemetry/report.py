"""Render a run summary from a JSONL run log (`ddt_tpu.cli report`).

Pure host-side post-processing: read_events -> summarize -> render. The
summary is a plain dict (the CLI's --json form); render() formats it for
a terminal. No jax, no device, no repo state — a run log copied off a
pod host reports anywhere.
"""

from __future__ import annotations

import json

from ddt_tpu.telemetry.events import partition_skew_summary, validate_event


def read_events(path: str) -> list[dict]:
    """Parse + validate a JSONL run log. Raises ValueError naming the
    line on a malformed record; a TRAILING partial line (the run was
    killed mid-write) is tolerated and dropped — everything above it is
    intact by the append-only write contract."""
    events: list[dict] = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines, start=1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if i == len(lines):
                # Torn FINAL line (the run was killed mid-write): the
                # crash-consistency contract (events.py) says everything
                # above it is intact, so drop just the tail. Records stay
                # schema-pure — no out-of-schema marker keys.
                break
            raise ValueError(f"{path}:{i}: not JSON: {e}") from None
        try:
            validate_event(rec)
        except ValueError as e:
            raise ValueError(f"{path}:{i}: {e}") from None
        events.append(rec)
    if not events:
        raise ValueError(f"{path}: no run-log events")
    return events


def _metric_key(rec: dict) -> str | None:
    for k in rec:
        if k.startswith("valid_"):
            return k
    return None


def _cross_host_totals(part_ev: list[dict]) -> dict:
    """{(host, device): {phase: ms}} accumulated over every host's
    partition_phases stream — the merged-log straggler recompute's
    input (device ids are lane-local per host's probe, so the composite
    key keeps hosts' lanes distinct even if ids collide)."""
    totals: dict = {}
    for e in part_ev:
        h = e.get("host", 0)
        for part in e["partitions"]:
            lane = totals.setdefault((h, part["device"]), {})
            for name, ms in part["phases"].items():
                lane[name] = lane.get(name, 0.0) + ms
    return totals


def summarize(events: list[dict], slowest: int = 5) -> dict:
    """Aggregate a run log into the report dict (see render for the
    shape as prose)."""
    # Append-mode logs can hold several run segments (a preemptible
    # restart re-runs the command into the same file; each fit emits its
    # own manifest). Report the LAST segment — the run that completed —
    # and surface the segment count so earlier attempts stay visible.
    # A cross-host MERGE (telemetry.merge) holds one manifest per host
    # for the SAME run: manifests sharing a run_id (v2) count as ONE
    # segment, and the report covers every host's events of that run.
    manifests = [e for e in events if e["event"] == "run_manifest"]
    hosts = sorted({m.get("host", 0) for m in manifests}) or [0]
    # Segment grouping: consecutive manifests join the current segment
    # only when they share its run_id AND come from a host not yet in it
    # (a restart re-derives the same config-deterministic run_id on the
    # same host — that is a new segment, not a new lane).
    segments: list[dict] = []          # {"first": manifest, "hosts": set}
    for m in manifests:
        rid = m.get("run_id")
        h = m.get("host", 0)
        cur = segments[-1] if segments else None
        if (cur is not None and rid is not None
                and cur["first"].get("run_id") == rid
                and h not in cur["hosts"]):
            cur["hosts"].add(h)
        else:
            segments.append({"first": m, "hosts": {h}})
    n_runs = len(segments)
    if segments:
        anchor = segments[-1]["first"]
        first = next(i for i, e in enumerate(events) if e is anchor)
        events = events[first:]
        hosts = sorted(segments[-1]["hosts"])   # the REPORTED segment's

    manifest = next((e for e in events if e["event"] == "run_manifest"), {})
    rounds = [e for e in events if e["event"] == "round"]
    if len(hosts) > 1:
        # Merged pod logs: every host emitted its own (SPMD-identical)
        # round records — report one lane's curve, not N copies.
        rounds = [r for r in rounds if r.get("host", 0) == hosts[0]]
    phase_ev = [e for e in events if e["event"] == "phase_timings"]
    counter_ev = [e for e in events if e["event"] == "counters"]
    cost_ev = [e for e in events if e["event"] == "cost_analysis"]
    if len(hosts) > 1 and phase_ev:
        # Merged pod logs: every host captured its own (SPMD-identical)
        # programs — join ONE host's cost stream against that SAME
        # host's phase wall-times (the reported phases are the last
        # phase_timings event's; summing all hosts' flops against one
        # host's wallclock would overstate achieved rates by the host
        # count).
        ph_host = phase_ev[-1].get("host", 0)
        cost_ev = [e for e in cost_ev if e.get("host", 0) == ph_host]
    hb_ev = [e for e in events if e["event"] == "train_heartbeat"]
    if len(hosts) > 1:
        # Same single-lane rule as the round curve: SPMD hosts emit
        # identical heartbeats.
        hb_ev = [e for e in hb_ev if e.get("host", 0) == hosts[0]]
    part_ev = [e for e in events if e["event"] == "partition_phases"]
    skew_ev = [e for e in events if e["event"] == "partition_skew"]
    cross_totals = (_cross_host_totals(part_ev)
                    if len(hosts) > 1 and part_ev else None)
    run_end = next((e for e in events if e["event"] == "run_end"), None)

    metric_curve = []
    metric = None
    for r in rounds:
        mk = _metric_key(r)
        if mk is not None:
            metric = mk[len("valid_"):]
            metric_curve.append({"round": r["round"], "score": r[mk]})
    losses = [{"round": r["round"], "train_loss": r["train_loss"]}
              for r in rounds if r.get("train_loss") is not None]

    timed = sorted((r for r in rounds if r.get("ms_per_round") is not None),
                   key=lambda r: -r["ms_per_round"])
    summary = {
        "manifest": {k: v for k, v in manifest.items()
                     if k not in ("event", "schema", "t", "seq")},
        "n_runs_in_log": n_runs,
        "n_round_records": len(rounds),
        "completed_rounds": run_end["completed_rounds"] if run_end else None,
        "wallclock_s": run_end["wallclock_s"] if run_end else None,
        "metric": metric,
        "metric_curve": metric_curve,
        "train_loss_curve": losses,
        "phases": phase_ev[-1]["phases"] if phase_ev else [],
        "counters": (
            {k: v for k, v in counter_ev[-1].items()
             if k not in ("event", "schema", "t", "seq")}
            if counter_ev else {}),
        "slowest_rounds": [
            {"round": r["round"], "ms_per_round": r["ms_per_round"]}
            for r in timed[:slowest]],
        "hosts": hosts,
        # Straggler view (distributed flight recorder): the run's
        # partition_skew reduction + how many rounds carried per-device
        # lanes (fused blocks cover `rounds` rounds per event; merged
        # logs count one host's stream, like the round curve above —
        # single logs are never host-filtered: a lone pod host's events
        # carry no host field). A single host's own skew event is used
        # verbatim (exact, as emitted); a MERGE recomputes over every
        # host's raw lanes, since each per-host event covers only its
        # addressable devices. Empty/None on single-device logs.
        "partition_skew": (
            partition_skew_summary(cross_totals)
            if cross_totals is not None
            else (skew_ev[-1]["phases"] if skew_ev else None)),
        "n_partitions": (
            len(cross_totals) if cross_totals is not None
            else (skew_ev[-1].get("n_partitions") if skew_ev else None)),
        "partition_rounds_observed": sum(
            e.get("rounds", 1) for e in part_ev
            if len(hosts) == 1 or e.get("host", 0) == hosts[0]),
        "early_stop": next(
            ({k: e[k] for k in ("round", "best_round", "best_score",
                                "metric")}
             for e in events if e["event"] == "early_stop"), None),
        "faults": [
            {k: v for k, v in e.items()
             if k not in ("event", "schema", "t", "seq")}
            for e in events if e["event"] == "fault"],
        # Device-truth cost observatory (schema v3): the raw
        # cost_analysis records (the diff tool reads them) — absent-as-
        # empty on pre-v3 logs.
        "cost_events": [
            {k: v for k, v in e.items()
             if k not in ("event", "schema", "t", "seq")}
            for e in cost_ev],
        # Serving tier (schema v4): SLO windows from ServeEngine.
        # emit_latency — None on pre-v4 / non-serving logs so older
        # summaries render exactly as before.
        "serving": _serving_summary(
            [e for e in events if e["event"] == "serve_latency"]),
        # Fleet rollup (ISSUE 15): per-model join of serve_latency
        # windows (the model_name dimension), eviction/reload lifecycle
        # faults, and artifact provenance — None unless some window
        # carries model_name, so single-model and pre-fleet logs render
        # exactly as before. `cli report --log L fleet` renders just
        # this table.
        "fleet": _fleet_summary(
            [e for e in events if e["event"] == "serve_latency"],
            [e for e in events if e["event"] == "fault"],
            [e for e in events if e["event"] == "artifact"]),
        # SLO rollup (ISSUE 17): per-model join of declared objectives
        # (the slo_p99_ms extra serve_latency windows carry) against
        # observed tails and slo_breach faults — None unless the log
        # carries EITHER signal, so pre-SLO logs render exactly as
        # before. `cli report --log L slo` renders just this table.
        "slo": _slo_summary(
            [e for e in events if e["event"] == "serve_latency"],
            [e for e in events if e["event"] == "fault"]),
        # Drift rollup (ISSUE 19): per-model join of latched `drift`
        # alert events against the drift_*/shadow_* extras riding
        # serve_latency windows — None unless the log carries EITHER
        # signal, so pre-drift logs render exactly as before.
        # `cli report --log L drift` renders just this table.
        "drift": _drift_summary(
            [e for e in events if e["event"] == "serve_latency"],
            [e for e in events if e["event"] == "drift"]),
        # Training-progress rollup (ISSUE 20): how far the run got, from
        # the checkpoint-cadence train_heartbeat events — the signal
        # built for logs of runs that DIED mid-round (read_events
        # tolerates the torn final line; the last intact heartbeat
        # still places the run). None on logs without heartbeats, so
        # every earlier log renders exactly as before. `cli report
        # --log L progress` renders just this table.
        "progress": _progress_summary(hb_ev, rounds, run_end,
                                      manifest),
        # Registry provenance (schema v5): artifact push/load events,
        # each cross-referenced against THIS run's id when they carry
        # one — None on pre-v5 logs.
        "registry": _registry_summary(
            [e for e in events if e["event"] == "artifact"],
            manifest.get("run_id")),
    }
    # Split-finding comms (ISSUE 10; manifest schema extras — absent on
    # single-device runs and every pre-existing log, which render
    # exactly as before).
    summary["comms"] = None
    if manifest.get("split_comms"):
        summary["comms"] = {
            "split_comms": manifest["split_comms"],
            "hist_comms_dtype": manifest.get("hist_comms_dtype", "f32"),
            "hist_comms_slabs": manifest.get("hist_comms_slabs", 1),
        }
    # Roofline join (telemetry/costmodel.py): only when the log carries
    # cost_analysis events — pre-v3 logs render exactly as before.
    summary["roofline"] = None
    if cost_ev and summary["phases"]:
        from ddt_tpu.telemetry.costmodel import roofline_table

        summary["roofline"] = roofline_table(
            summary["phases"], summary["cost_events"],
            counters=summary["counters"],
            wallclock_s=summary["wallclock_s"])
    return summary


def _serving_summary(serve_ev: list[dict]) -> dict | None:
    """Reduce a run's serve_latency windows for the report: totals
    across windows, the LAST window's quantiles (current behavior), and
    the WORST p99/p999 seen in any window (tail attribution wants the
    worst window, not the most recent one)."""
    if not serve_ev:
        return None
    last = serve_ev[-1]
    return {
        "windows": len(serve_ev),
        "requests": sum(e["requests"] for e in serve_ev),
        "batches": sum(e.get("batches", 0) for e in serve_ev),
        "p50_ms": last["p50_ms"],
        "p99_ms": last["p99_ms"],
        "p999_ms": last.get("p999_ms"),
        "worst_p99_ms": max(e["p99_ms"] for e in serve_ev),
        "worst_p999_ms": max((e.get("p999_ms") or 0.0)
                             for e in serve_ev) or None,
        "coalesce_mean": last.get("coalesce_mean"),
        "coalesce_max": max((e.get("coalesce_max") or 0)
                            for e in serve_ev),
        "queue_depth_max": max((e.get("queue_depth_max") or 0)
                               for e in serve_ev),
        # ISSUE 12 extras (None/0 on pre-int4 logs): the quantization
        # tier that ACTUALLY served the last window, and how much
        # traffic rode the express lane across all windows.
        "predict_impl": last.get("predict_impl"),
        "express": sum(e.get("express", 0) or 0 for e in serve_ev),
        "model_tokens": sorted({e["model_token"][:12] for e in serve_ev
                                if e.get("model_token")}),
    }


def _fleet_summary(serve_ev: list[dict], fault_ev: list[dict],
                   artifact_ev: list[dict]) -> dict | None:
    """Per-model fleet rollup: every model's serve_latency windows,
    the tier that actually served its last window, eviction/reload
    counts (fleet_eviction/fleet_reload faults), and the artifact each
    model served (joined to the artifact events' name@version/run_id
    provenance by digest). None unless the log carries the model_name
    dimension — pre-fleet logs summarize exactly as before."""
    named = [e for e in serve_ev if e.get("model_name")]
    if not named:
        return None
    models: dict = {}

    def rec(name) -> dict:
        return models.setdefault(name, {
            "windows": 0, "requests": 0, "express": 0,
            "p50_ms": None, "p99_ms": None, "worst_p99_ms": None,
            "tier": None, "model_token": None, "artifact_digest": None,
            "evictions": 0, "reloads": 0, "artifact": None,
        })

    for e in named:
        m = rec(e["model_name"])
        m["windows"] += 1
        m["requests"] += e["requests"]
        m["express"] += e.get("express", 0) or 0
        m["p50_ms"] = e["p50_ms"]            # last window's quantiles
        m["p99_ms"] = e["p99_ms"]
        m["worst_p99_ms"] = max(m["worst_p99_ms"] or 0.0, e["p99_ms"])
        m["tier"] = e.get("predict_impl") or m["tier"]
        m["model_token"] = e.get("model_token") or m["model_token"]
        m["artifact_digest"] = (e.get("artifact_digest")
                                or m["artifact_digest"])
    for f in fault_ev:
        name = f.get("model_name")
        if not name:
            continue
        if f.get("kind") == "fleet_eviction":
            rec(name)["evictions"] += 1
        elif f.get("kind") == "fleet_reload":
            rec(name)["reloads"] += 1
    # Provenance join: the artifact event stream knows name@version,
    # run_id, and restore mode per digest — attach each model's.
    by_digest = {}
    for a in artifact_ev:
        d = a.get("digest")
        if d:
            by_digest[d] = {
                "name": a.get("name"), "version": a.get("version"),
                "run_id": a.get("run_id"), "mode": a.get("mode")}
    for m in models.values():
        if m["artifact_digest"]:
            m["artifact"] = by_digest.get(m["artifact_digest"])
    return {
        "models": dict(sorted(models.items())),
        "evictions": sum(m["evictions"] for m in models.values()),
        "reloads": sum(m["reloads"] for m in models.values()),
    }


def _slo_summary(serve_ev: list[dict],
                 fault_ev: list[dict]) -> dict | None:
    """Per-model SLO rollup (ISSUE 17): join declared objectives (the
    slo_p99_ms extra on serve_latency windows) against the observed
    tail and the run's slo_breach faults (burn rate at the transition).
    Mixed-era logs degrade gracefully by construction: pre-SLO windows
    simply carry no objective (rendered `-`, never an error), and a
    model that breached before ever emitting a window enters the table
    through its faults alone — objective recovered from the breach
    event's own objective_ms, quantiles honestly absent. None when the
    log carries neither signal, so pre-SLO logs summarize exactly as
    before."""
    breaches = [f for f in fault_ev if f.get("kind") == "slo_breach"]
    objective_windows = [e for e in serve_ev if e.get("slo_p99_ms")]
    if not breaches and not objective_windows:
        return None
    models: dict = {}

    def rec(name) -> dict:
        return models.setdefault(name, {
            "objective_ms": None, "windows": 0, "requests": 0,
            "p99_ms": None, "worst_p99_ms": None,
            "breaches": 0, "max_burn_rate": None,
        })

    for e in serve_ev:
        name = e.get("model_name") or "default"
        # Only SLO-era windows open a row; older windows still fold
        # into an existing row's tail so the worst p99 is honest.
        if not e.get("slo_p99_ms") and name not in models:
            continue
        m = rec(name)
        m["objective_ms"] = e.get("slo_p99_ms") or m["objective_ms"]
        m["windows"] += 1
        m["requests"] += e["requests"]
        m["p99_ms"] = e["p99_ms"]            # last window's tail
        m["worst_p99_ms"] = max(m["worst_p99_ms"] or 0.0, e["p99_ms"])
    for f in breaches:
        m = rec(f.get("model_name") or "default")
        m["breaches"] += 1
        if m["objective_ms"] is None:
            m["objective_ms"] = f.get("objective_ms")
        burn = f.get("burn_rate")
        if burn is not None:
            m["max_burn_rate"] = max(m["max_burn_rate"] or 0.0, burn)
    return {
        "models": dict(sorted(models.items())),
        "breaches": len(breaches),
    }


def _drift_summary(serve_ev: list[dict],
                   drift_ev: list[dict]) -> dict | None:
    """Per-model drift rollup (ISSUE 19): join the observatory's two
    log signals — latched `drift` alert events and the drift_*/shadow_*
    extras serve_latency windows carry — into one table. Mixed-era logs
    degrade gracefully by construction: pre-drift windows simply carry
    no divergence (rendered `-`, never an error), and a model that
    alerted before ever emitting a window enters the table through its
    events alone. None when the log carries neither signal, so
    pre-drift logs summarize exactly as before."""
    windows = [e for e in serve_ev
               if e.get("drift_psi_max") is not None
               or e.get("shadow_model")]
    if not drift_ev and not windows:
        return None
    models: dict = {}

    def rec(name) -> dict:
        return models.setdefault(name, {
            "windows": 0, "requests": 0,
            "psi_max": None, "worst_psi_max": None, "js_max": None,
            "alerting": False, "alerts": 0,
            "worst_feature": None, "threshold": None,
            "shadow": None,
        })

    for e in serve_ev:
        name = e.get("model_name") or "default"
        has_drift = e.get("drift_psi_max") is not None
        has_shadow = bool(e.get("shadow_model"))
        # Only drift-era windows open a row; older windows still fold
        # into an existing row's traffic so the request count is honest.
        if not has_drift and not has_shadow and name not in models:
            continue
        m = rec(name)
        m["windows"] += 1
        m["requests"] += e["requests"]
        if has_drift:
            m["psi_max"] = e["drift_psi_max"]     # last window's score
            m["js_max"] = e.get("drift_js_max")
            m["worst_psi_max"] = max(m["worst_psi_max"] or 0.0,
                                     e["drift_psi_max"])
            m["alerting"] = bool(e.get("drift_alerting"))
        if has_shadow:
            m["shadow"] = {
                "model": e["shadow_model"],
                "rows": e.get("shadow_rows"),
                "mean_abs_diff": e.get("shadow_mean_abs_diff"),
                "ms_p50": e.get("shadow_ms_p50"),
                "dropped": e.get("shadow_dropped", 0) or 0,
            }
    for d in drift_ev:
        m = rec(d.get("model_name") or "default")
        m["alerts"] += 1
        m["worst_psi_max"] = max(m["worst_psi_max"] or 0.0,
                                 d["psi_max"])
        m["worst_feature"] = d.get("feature", m["worst_feature"])
        m["threshold"] = d.get("threshold") or m["threshold"]
    return {"models": dict(sorted(models.items())),
            "alerts": len(drift_ev)}


def _progress_summary(hb_ev: list[dict], rounds: list[dict],
                      run_end, manifest: dict) -> dict | None:
    """Training-progress rollup (ISSUE 20): reduce the checkpoint-
    cadence train_heartbeat events into "how far did this run get" —
    the question asked about a run log whose process died mid-round
    (no run_end, possibly a torn final line). The furthest round is the
    max over heartbeats AND intact round records, so a run that died
    between heartbeats is still placed as precisely as the log allows.
    None when the log carries no heartbeats, so every pre-ISSUE-20 log
    summarizes exactly as before."""
    if not hb_ev:
        return None
    last_hb = max((h.get("round", 0) for h in hb_ev), default=0)
    last_rec = max((r.get("round", 0) for r in rounds), default=0)
    last_round = max(last_hb, last_rec)
    total = (hb_ev[-1].get("total_rounds")
             or manifest.get("n_trees"))
    ckpt = next((h["checkpoint_round"] for h in reversed(hb_ev)
                 if h.get("checkpoint_round") is not None), None)
    return {
        "heartbeats": len(hb_ev),
        "last_round": last_round,
        "total_rounds": total,
        "pct": (round(100.0 * last_round / total, 1)
                if total else None),
        "last_checkpoint_round": ckpt,
        # A run_end event means the epilogue ran — the run FINISHED
        # (possibly early-stopped); its absence is the mid-run-death
        # signal this rollup exists for.
        "completed": run_end is not None,
        "beats": [
            {k: h.get(k) for k in ("round", "total_rounds",
                                   "checkpoint_round", "ms_per_round",
                                   "rows_per_s")}
            for h in hb_ev],
    }


def _registry_summary(artifact_ev: list[dict],
                      log_run_id) -> dict | None:
    """Reduce a run's artifact events for the report: one record per
    event (they are rare — lifecycle steps, not request traffic), with
    `same_run` marking artifacts whose embedded training run_id matches
    this log's own manifest — the provenance join the registry exists
    to provide (train --run-log L; registry push; report --log L shows
    the push against its own run)."""
    if not artifact_ev:
        return None
    events = []
    for e in artifact_ev:
        rec = {
            "action": e["action"],
            "digest": e["digest"],
            "name": e.get("name"),
            "version": e.get("version"),
            "run_id": e.get("run_id"),
            "mode": e.get("mode"),
            "same_run": (e.get("run_id") is not None
                         and e.get("run_id") == log_run_id),
        }
        events.append(rec)
    return {
        "events": events,
        "pushes": sum(1 for e in events if e["action"] == "push"),
        "loads": sum(1 for e in events if e["action"] == "load"),
        "digests": sorted({e["digest"] for e in events if e["digest"]}),
    }


def _fmt_bytes(n) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n}"


def render_fleet(summary: dict) -> str:
    """The `report fleet` rollup: one row per model joining its SLO
    windows, serving tier, eviction/reload counts, and artifact
    provenance (docs/OBSERVABILITY.md). Raises ValueError when the log
    carries no fleet data (no model_name-dimensioned windows)."""
    fl = summary.get("fleet")
    if not fl:
        raise ValueError(
            "log carries no fleet serve_latency windows (no model_name "
            "dimension) — is this a single-model serve log?")
    out = [f"fleet: {len(fl['models'])} model(s), "
           f"{fl['evictions']} eviction(s), {fl['reloads']} reload(s)"]
    out.append(
        f"  {'model':<12} {'reqs':>7} {'win':>4} {'p50_ms':>8} "
        f"{'p99_ms':>8} {'worst_p99':>9} {'tier':<5} {'evic':>4} "
        f"{'reld':>4}  artifact")
    def ms(v) -> str:
        # A model can enter the rollup through lifecycle faults alone
        # (evicted before it ever served a window) — its quantiles are
        # honestly absent, not zero.
        return f"{v:>8.3f}" if v is not None else f"{'-':>8}"

    for name, m in fl["models"].items():
        art = m.get("artifact_digest") or "-"
        prov = m.get("artifact")
        if prov and prov.get("name") and prov.get("version") is not None:
            art += f" ({prov['name']}@{prov['version']}"
            if prov.get("mode"):
                art += f", {prov['mode']}"
            art += ")"
        out.append(
            f"  {name:<12} {m['requests']:>7} {m['windows']:>4} "
            f"{ms(m['p50_ms'])} {ms(m['p99_ms'])} "
            f"{ms(m['worst_p99_ms']):>9} "
            f"{(m['tier'] or '-'):<5} {m['evictions']:>4} "
            f"{m['reloads']:>4}  {art}")
    return "\n".join(out)


def render_slo(summary: dict) -> str:
    """The `report slo` rollup: one row per model joining its declared
    p99 objective against the observed tail and the run's slo_breach
    burn rates (docs/OBSERVABILITY.md). Absent values — a pre-SLO
    window's objective, a breached-before-first-window model's
    quantiles — render `-`, never an error. Raises ValueError when the
    log carries no SLO signal at all (no objectives, no breaches)."""
    slo = summary.get("slo")
    if not slo:
        raise ValueError(
            "log carries no SLO data (no slo_p99_ms objectives on "
            "serve_latency windows and no slo_breach faults) — was "
            "this server configured with an SLO (slo_p99_ms=)?")

    def ms(v) -> str:
        return f"{v:>9.3f}" if v is not None else f"{'-':>9}"

    out = [f"slo: {len(slo['models'])} model(s), "
           f"{slo['breaches']} breach(es)"]
    out.append(
        f"  {'model':<12} {'objective':>9} {'p99_ms':>9} "
        f"{'worst_p99':>9} {'win':>4} {'reqs':>7} {'breach':>6} "
        f"{'max_burn':>8}")
    for name, m in slo["models"].items():
        burn = (f"{m['max_burn_rate']:>8.2f}"
                if m.get("max_burn_rate") is not None else f"{'-':>8}")
        out.append(
            f"  {name:<12} {ms(m['objective_ms'])} {ms(m['p99_ms'])} "
            f"{ms(m['worst_p99_ms'])} {m['windows']:>4} "
            f"{m['requests']:>7} {m['breaches']:>6} {burn}")
    return "\n".join(out)


def render_drift(summary: dict) -> str:
    """The `report drift` rollup: one row per model joining rolling-
    window divergence (PSI / JS against the training reference) with
    latched drift alerts, plus one champion/challenger line per
    shadowed model (docs/OBSERVABILITY.md "Drift observatory"). Absent
    values — a pre-drift window's divergence, an alert-only model's
    window stats — render `-`, never an error. Raises ValueError when
    the log carries no drift signal at all (no drift events, no
    drift/shadow window extras)."""
    dr = summary.get("drift")
    if not dr:
        raise ValueError(
            "log carries no drift data (no drift events and no "
            "drift_*/shadow_* extras on serve_latency windows) — did "
            "this fleet serve an artifact with a training reference "
            "histogram (drift_reference)?")

    def f(v) -> str:
        return f"{v:>8.4f}" if v is not None else f"{'-':>8}"

    out = [f"drift: {len(dr['models'])} model(s), "
           f"{dr['alerts']} alert(s)"]
    out.append(
        f"  {'model':<12} {'psi_max':>8} {'worst':>8} {'js_max':>8} "
        f"{'win':>4} {'reqs':>7} {'alerts':>6} {'state':<8} feature")
    for name, m in dr["models"].items():
        state = "ALERTING" if m["alerting"] else "ok"
        feat = m["worst_feature"] if m["worst_feature"] is not None \
            else "-"
        out.append(
            f"  {name:<12} {f(m['psi_max'])} {f(m['worst_psi_max'])} "
            f"{f(m['js_max'])} {m['windows']:>4} {m['requests']:>7} "
            f"{m['alerts']:>6} {state:<8} {feat}")
    for name, m in dr["models"].items():
        sh = m.get("shadow")
        if not sh:
            continue
        diff = (f"mean_abs_diff={sh['mean_abs_diff']:.6f}"
                if sh.get("mean_abs_diff") is not None
                else "mean_abs_diff=-")
        p50 = (f"p50={sh['ms_p50']:.3f} ms"
               if sh.get("ms_p50") is not None else "p50=-")
        out.append(
            f"  shadow {sh['model']} -> {name}: "
            f"rows={sh.get('rows') or 0}  {diff}  {p50}  "
            f"dropped={sh['dropped']}")
    return "\n".join(out)


def render_progress(summary: dict) -> str:
    """The `report progress` rollup: round reached vs total, the last
    checkpoint round, and one row per heartbeat with its pace
    (docs/OBSERVABILITY.md "Training operations plane"). Raises
    ValueError on a log with no train_heartbeat events — the loud
    failure `cli report progress` converts into a clean SystemExit."""
    pg = summary.get("progress")
    if not pg:
        raise ValueError(
            "log carries no training heartbeat data (no "
            "train_heartbeat events) — heartbeats are emitted at "
            "checkpoint cadence by schema-v5+ training runs; was this "
            "log written by an older run, or did the run die before "
            "the first checkpoint boundary?")
    state = "completed" if pg["completed"] else "DIED MID-RUN"
    total = pg["total_rounds"]
    pct = f" ({pg['pct']:.1f}%)" if pg.get("pct") is not None else ""
    ckpt = (str(pg["last_checkpoint_round"])
            if pg.get("last_checkpoint_round") is not None else "-")
    out = [
        f"progress: round {pg['last_round']}/{total or '?'}{pct}  "
        f"[{state}]  heartbeats={pg['heartbeats']}  "
        f"last_checkpoint={ckpt}"]
    out.append(
        f"  {'round':>6} {'ms/round':>9} {'rows/s':>10} {'ckpt':>5}")
    for h in pg["beats"]:
        ms = (f"{h['ms_per_round']:>9.1f}"
              if h.get("ms_per_round") is not None else f"{'-':>9}")
        rps = (f"{h['rows_per_s']:>10.1f}"
               if h.get("rows_per_s") is not None else f"{'-':>10}")
        ck = (str(h["checkpoint_round"])
              if h.get("checkpoint_round") is not None else "-")
        out.append(f"  {h.get('round', 0):>6} {ms} {rps} {ck:>5}")
    return "\n".join(out)


def render(summary: dict) -> str:
    """Terminal rendering of summarize()'s dict."""
    out: list[str] = []
    m = summary["manifest"]
    head = " ".join(
        f"{k}={m[k]}" for k in ("trainer", "backend", "loss", "n_trees",
                                "max_depth", "rows", "features") if k in m)
    out.append(f"run: {head or '(no manifest)'}")
    if summary.get("n_runs_in_log", 1) > 1:
        out.append(f"note: log holds {summary['n_runs_in_log']} run "
                   "segments; reporting the last")
    done = summary["completed_rounds"]
    wc = summary["wallclock_s"]
    out.append(
        f"rounds: {summary['n_round_records']} recorded"
        + (f", {done} completed" if done is not None else "")
        + (f", {wc:.2f}s wallclock" if wc is not None else ""))

    if summary["early_stop"]:
        es = summary["early_stop"]
        out.append(
            f"early stop at round {es['round']} "
            f"(best {es['metric']}={es['best_score']:.6f} "
            f"at round {es['best_round']})")
    for f in summary["faults"]:
        detail = {k: v for k, v in f.items() if k != "kind"}
        out.append(f"fault/recovery: {f['kind']} {detail or ''}".rstrip())

    if len(summary.get("hosts", [0])) > 1:
        out.append(f"hosts: {len(summary['hosts'])} merged "
                   f"({', '.join(str(h) for h in summary['hosts'])})")

    if summary["phases"]:
        out.append("phases (host wallclock):")
        for p in summary["phases"]:
            out.append(
                f"  {p['phase']:<14} {p['ms_total']:>9.1f} ms total  "
                f"{p['ms_per_call']:>8.2f} ms/call  x{p['calls']:<6} "
                f"{100 * p['share']:5.1f}%")

    if summary.get("roofline"):
        out.append("roofline (XLA cost model vs host wallclock; "
                   "achieved against per-platform peak ceilings):")
        for r in summary["roofline"]:
            if r.get("coll_util") is not None:
                dev = (f"{r['gbs']:>8.2f} GB/s wire "
                       f"({100 * r['coll_util']:5.1f}% interconnect)")
            elif r.get("gflops") is None:
                dev = "no device cost registered"
            else:
                dev = (f"{r['gflops']:>9.2f} GFLOP/s "
                       f"({100 * r['flops_util']:5.1f}%)  "
                       f"{r['gbs']:>8.2f} GB/s "
                       f"({100 * r['hbm_util']:5.1f}%)")
            out.append(
                f"  {r['phase']:<14} {r['ms']:>9.1f} ms  {dev:<44} "
                f"-> {r['verdict']}")

    if summary.get("partition_skew"):
        n = summary.get("n_partitions")
        out.append(
            f"partitions ({n} lanes, "
            f"{summary.get('partition_rounds_observed', 0)} rounds "
            "observed; straggler = max/median completion):")
        for p in summary["partition_skew"]:
            skew = f"{p['skew']:.2f}x" if p.get("skew") is not None \
                else "n/a"
            where = (f"h{p['max_host']}/dev{p['max_device']}"
                     if "max_host" in p else f"dev{p['max_device']}")
            out.append(
                f"  {p['phase']:<14} max {p['ms_max']:>9.1f} ms "
                f"@{where:<8} median "
                f"{p['ms_median']:>9.1f} ms  skew {skew}")

    if summary.get("serving"):
        s = summary["serving"]
        out.append(
            f"serving: {s['requests']} requests in {s['windows']} "
            f"window(s), {s['batches']} micro-batches  "
            f"(coalesce max {s['coalesce_max']}, "
            f"queue depth max {s['queue_depth_max']})")
        p999 = (f"  p999={s['p999_ms']:.3f} ms"
                if s.get("p999_ms") is not None else "")
        worst = (f"  worst-window p99={s['worst_p99_ms']:.3f} ms"
                 if s.get("worst_p99_ms") is not None else "")
        out.append(
            f"  latency: p50={s['p50_ms']:.3f} ms  "
            f"p99={s['p99_ms']:.3f} ms{p999}{worst}")
        extras = []
        if s.get("predict_impl"):
            extras.append(f"tier={s['predict_impl']}")
        if s.get("express"):
            extras.append(f"express={s['express']}")
        if extras:
            out.append("  " + "  ".join(extras))
        if s.get("model_tokens"):
            out.append("  models served: "
                       + ", ".join(s["model_tokens"]))

    if summary.get("fleet"):
        out.append(render_fleet(summary))

    if summary.get("slo"):
        out.append(render_slo(summary))

    if summary.get("drift"):
        out.append(render_drift(summary))

    if summary.get("registry"):
        r = summary["registry"]
        out.append(
            f"registry: {r['pushes']} push(es), {r['loads']} load(s) "
            f"across {len(r['digests'])} artifact(s)")
        for e in r["events"]:
            where = (f"{e['name']}@{e['version']}"
                     if e.get("name") and e.get("version") else "")
            bits = [b for b in (
                where,
                e["digest"],
                f"mode={e['mode']}" if e.get("mode") else "",
                f"run_id={e['run_id']}" + (
                    " (this run)" if e["same_run"] else "")
                if e.get("run_id") else "",
            ) if b]
            out.append(f"  {e['action']:<5} " + "  ".join(bits))

    curve = summary["metric_curve"]
    if curve:
        name = summary["metric"]
        first, last = curve[0], curve[-1]
        # Direction from the ONE metrics table (utils.metrics) — a copy
        # here would silently label the worst round "best" for any
        # metric added there later. Unknown names (a log from a newer
        # build) default to lower-is-better, the loss convention.
        from ddt_tpu.utils.metrics import GREATER_IS_BETTER

        best = max(curve, key=lambda c: c["score"]) \
            if GREATER_IS_BETTER.get(name, False) \
            else min(curve, key=lambda c: c["score"])
        out.append(
            f"valid_{name}: first={first['score']:.6f} "
            f"(round {first['round']})  best={best['score']:.6f} "
            f"(round {best['round']})  last={last['score']:.6f} "
            f"(round {last['round']})  [{len(curve)} rounds]")
    losses = summary["train_loss_curve"]
    if losses:
        out.append(
            f"train_loss: first={losses[0]['train_loss']:.6f} "
            f"(round {losses[0]['round']})  "
            f"last={losses[-1]['train_loss']:.6f} "
            f"(round {losses[-1]['round']})")

    c = summary["counters"]
    if c:
        compile_s = c.get("jit_compile_seconds")
        out.append(
            "counters: "
            f"jit_compiles={c.get('jit_compiles')}"
            + (f" ({compile_s:.2f}s compiling)"
               if compile_s is not None else "")
            + "  "
            f"h2d={_fmt_bytes(c.get('h2d_bytes'))}  "
            f"d2h={_fmt_bytes(c.get('d2h_bytes'))}  "
            f"collective≈{_fmt_bytes(c.get('collective_bytes_est'))}  "
            f"device_peak={_fmt_bytes(c.get('device_peak_bytes'))}  "
            f"host_rss_peak={_fmt_bytes(c.get('host_peak_rss_bytes'))}")
        # Per-mode comms line (ISSUE 10): the resolved split-finding
        # collective + wire dtype next to the EFFECTIVE payload the
        # counter above already reflects (subtraction-halved levels,
        # scattered slabs, compressed entries).
        cm = summary.get("comms")
        if cm:
            out.append(
                "comms: "
                f"split_comms={cm['split_comms']}  "
                f"wire_dtype={cm['hist_comms_dtype']}  "
                f"slabs={cm['hist_comms_slabs']}  "
                f"payload≈{_fmt_bytes(c.get('collective_bytes_est'))} "
                "(effective)")
        # Scoring-cache effectiveness (absent in pre-overhaul logs).
        hits = c.get("compiled_ensemble_cache_hits")
        if hits is not None:
            out.append(f"predict: compiled_ensemble_cache_hits={hits}")
        # Robustness health pair (docs/ROBUSTNESS.md): nonzero means the
        # run limped through faults — say so even when it finished green.
        retries = c.get("fault_retries") or 0
        degrades = c.get("hist_oom_degrades") or 0
        if retries or degrades:
            out.append(f"robustness: fault_retries={retries}  "
                       f"hist_oom_degrades={degrades}")

    if summary["slowest_rounds"]:
        slow = ", ".join(f"#{r['round']} ({r['ms_per_round']:.1f} ms)"
                         for r in summary["slowest_rounds"])
        out.append(f"slowest rounds: {slow}")
    return "\n".join(out)
