"""Cross-host run-log merge: N per-host JSONL logs -> one timeline.

Multi-host training (parallel/mesh.initialize_multihost) is SPMD: every
host runs the same program and writes its OWN run log with its OWN
clock. This module joins those logs into a single event stream the
report and trace consumers can read as one run:

- **Join key**: the manifests' `run_id` (schema v2, a deterministic
  config digest — telemetry.events.derive_run_id — identical on every
  host by SPMD construction). Logs whose manifests carry DIFFERENT run
  ids are refused loudly: merging unrelated runs silently is the worst
  failure mode a merge tool can have. Pre-v2 logs without run ids merge
  on trust (the caller named the files).
- **Clock offset**: estimated from the manifests — every host emits its
  manifest at the same program point (right before the first upload, a
  breath after the collective bootstrap barrier), so
  `offset_h = t_manifest_h - t_manifest_0` captures wall-clock skew up
  to the bootstrap jitter. Adjusted times are host-0's clock.
- **Deterministic ordering**: events sort by (adjusted t, host, seq) —
  a total order, so the merged stream is byte-stable no matter the
  input file order (tested with interleaved rounds + a fabricated
  offset).

Every merged event gains/keeps a `host` field (from its manifest, else
the input position) so per-host lanes survive into `report` and the
Perfetto export.
"""

from __future__ import annotations

from ddt_tpu.telemetry.report import read_events


def _manifest(events: list[dict]) -> dict | None:
    for e in events:
        if e["event"] == "run_manifest":
            return e
    return None


def merge_events(per_host: list[list[dict]]) -> list[dict]:
    """Merge N hosts' event lists (each a validated read_events result)
    into one host-0-clock timeline. Returns NEW event dicts (inputs are
    not mutated); raises ValueError on run-id mismatch or a hostless
    log list."""
    if not per_host:
        raise ValueError("merge needs at least one event list")
    manifests = []
    for i, events in enumerate(per_host):
        m = _manifest(events)
        if m is None:
            raise ValueError(f"input {i}: no run_manifest — cannot "
                             "estimate its clock offset")
        manifests.append(m)
    run_ids = {m.get("run_id") for m in manifests}
    if len(run_ids) > 1 and run_ids != {None}:
        raise ValueError(
            f"refusing to merge logs from different runs: run_ids="
            f"{sorted(str(r) for r in run_ids)} (the merge key is the "
            "manifest run_id; these logs were not written by one run)")
    # Host labels: the manifests' own `host` where stamped (v2);
    # pre-v2 hostless logs are labelled by MANIFEST-TIME rank — a
    # property of the logs, not of argument order, so the merged
    # stream stays byte-identical no matter how the shell glob ordered
    # the files.
    unlabelled = sorted(
        (i for i, m in enumerate(manifests) if "host" not in m),
        key=lambda i: (manifests[i]["t"], manifests[i].get("seq", 0)))
    rank = {idx: r for r, idx in enumerate(unlabelled)}
    hosts = [m.get("host", rank.get(i)) for i, m in enumerate(manifests)]
    # Reference clock: the lowest-numbered host.
    ref = min(range(len(manifests)), key=lambda i: (hosts[i],
                                                    manifests[i]["t"]))
    t0 = manifests[ref]["t"]
    merged: list[dict] = []
    for i, events in enumerate(per_host):
        offset = manifests[i]["t"] - t0
        host = hosts[i]
        for e in events:
            rec = dict(e)
            rec["t"] = rec["t"] - offset
            rec.setdefault("host", host)
            merged.append(rec)
    merged.sort(key=lambda e: (e["t"], e["host"], e["seq"]))
    return merged


def merge_paths(paths: list[str]) -> list[dict]:
    """read_events + merge_events over JSONL paths — the `report` /
    `trace` CLI entry (a single path passes through un-merged, so the
    one-log case costs nothing new)."""
    if len(paths) == 1:
        return read_events(paths[0])
    return merge_events([read_events(p) for p in paths])
