"""Host/device phase annotations sharing ONE naming scheme: `ddt:<phase>`.

Two halves of the Perfetto-alignment story (docs/OBSERVABILITY.md):

- phase_span(name): HOST-side jax.profiler.TraceAnnotation. The Driver
  enters it around each PhaseTimer phase, so a profiler capture
  (--trace-dir) shows `ddt:grow`, `ddt:eval`, ... spans on the host
  track with exactly the names the run log's phase_timings carry.
- traced_scope(name): jax.named_scope for use INSIDE traced code. The
  ops kernels wrap their hist/allreduce/gain/route/leaf/predict stages,
  which names the lowered XLA ops — the device timeline then carries
  the same `ddt:` prefixes and lines up under the host spans.

Both degrade to no-ops without jax (the cpu-backend CLI contract) and
cost ~a microsecond when no trace is being captured — cheap enough to
leave on whenever a PhaseTimer is running, and absent entirely (the
Driver skips the context) when telemetry is off.
"""

from __future__ import annotations

import contextlib
import functools

try:
    import jax
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except ImportError:               # jax-less host: annotations are no-ops
    jax = None
    _TraceAnnotation = None

PREFIX = "ddt:"


def phase_span(name: str):
    """Host-side profiler span `ddt:<name>` (no-op without jax)."""
    if _TraceAnnotation is None:
        return contextlib.nullcontext()
    return _TraceAnnotation(PREFIX + name)


def traced_scope(name: str):
    """Named scope `ddt:<name>` for code under jit (no-op without jax)."""
    if jax is None:
        return contextlib.nullcontext()
    return jax.named_scope(PREFIX + name)


def op_scope(name: str):
    """Whole-function traced_scope as a decorator — the canonical fix for
    ddtlint's `named-scope` rule on op ENTRY POINTS whose entire body is
    one pipeline stage (a `with` block would just re-indent the full
    function). Composes under jit: place it BELOW the @jit/@partial(jax.
    jit, ...) decorator; functools.wraps preserves the signature, so
    static_argnames keep resolving. Trace-time-only indirection — the
    lowered HLO carries `ddt:<name>` metadata and the runtime never sees
    the wrapper again after compilation."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with traced_scope(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def phase_ctx(timer):
    """Phase-context factory — the ONE home of the PhaseTimer +
    phase_span pairing, shared by the Driver's granular and fused loops
    and both streaming loops (keeping span naming/ordering from
    diverging between trainers). `timer` is a utils.profiling.PhaseTimer
    or None; with None the factory returns bare nullcontexts so
    disabled-telemetry hot loops stay unannotated."""
    if timer is None:
        def ph(name):
            return contextlib.nullcontext()
    else:
        def ph(name):
            stack = contextlib.ExitStack()
            stack.enter_context(phase_span(name))
            stack.enter_context(timer.phase(name))
            return stack
    return ph
