"""AOT model export (jax.export / StableHLO) — see export/aot.py and
docs/REGISTRY.md. Import the submodule lazily (`from ddt_tpu.export
import aot`): it needs jax, and the registry's pure-metadata paths
(list/tag/manifest reads) must work without it."""
