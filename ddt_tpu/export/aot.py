"""AOT export of the compiled predict function (jax.export / StableHLO).

The Julia→TPU full-compilation work (arXiv:1810.09868) showed that the
right deployment boundary for accelerator ML is the WHOLE lowered
program, not source that re-traces at the destination. This module is
that boundary for a trained ensemble: per pad-to-bucket batch shape,
the scoring function is lowered once (in the exporting process), the
StableHLO serialized, and the bytes shipped inside the registry
artifact. A cold serving process deserializes and compiles each bucket
at load time — it never re-traces the model, which the `jit_compiles`
counter witnesses (`make registry-smoke`).

Two variants per artifact (docs/REGISTRY.md "Artifact layout"):

- **f32** — `predict_raw_effective` over the CompiledEnsemble's
  pushed-down arrays with `use_pallas=False`: the one-hot path is pure
  StableHLO (no platform custom calls), so a single export lowers for
  BOTH cpu and tpu (`platforms=("cpu","tpu")`) and the same blob serves
  on chip or host. Bit-exact to the in-process path by the repo's
  standing parity contracts (pallas == one-hot, tests/test_predict_*).
- **lut** — the TreeLUT int8 fast path (ops/predict_lut.py,
  arXiv:2501.01511). The kernel is a Pallas call, so the export is
  platform-specific (interpret-mode lowering on host, the real kernel
  on chip); the manifest records `lut_platforms` and the loader falls
  back to rebuilding the LUT path from the carried tables when the
  serving platform differs. The quantized tables THEMSELVES also ride
  in the artifact (`lut_tables.npz`) so the int8 representation — and
  its computed `max_abs_err` bound — survives export verbatim.

The exported functions take `(*operands, X)` where the operands are
exactly `CompiledEnsemble.arrays()` / `lut_device_operands(tables)` —
the loader rebuilds those host-side from model.npz (deterministic;
guarded by the manifest's `model_token`) and keeps them device-resident
across requests, so the blobs stay small (program only, no weights).
"""

from __future__ import annotations

import dataclasses
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

from ddt_tpu.ops import predict as predict_ops
from ddt_tpu.ops import predict_lut
from ddt_tpu.registry import manifest as manifest_mod

log = logging.getLogger("ddt_tpu.export")

MODEL_FILE = "model.npz"
LUT_TABLES_FILE = "lut_tables.npz"
AOT_DIR = "aot"
F32_BLOB = "predict_f32_b{bucket:05d}.bin"
LUT_BLOB = "predict_lut_b{bucket:05d}.bin"
#: int4 bit-packed tier (ISSUE 12) — its own blob family: the operand
#: layout (packed nibbles) differs from the int8 tier's, and a
#: self-describing name beats decoding the manifest to tell them apart.
LUT4_BLOB = "predict_lut4_b{bucket:05d}.bin"
#: platforms one f32 export covers when multi-platform lowering works
#: (pure StableHLO — no custom calls — so lowering for the absent
#: platform needs no hardware).
F32_PLATFORMS = ("cpu", "tpu")


def f32_predict_fn(ce):
    """The f32 scoring closure over a CompiledEnsemble's static facts —
    the SAME computation TPUDevice._predict_fn jits (one-hot form), so
    an exported call is bit-identical to the exporting process's serve
    path at the same shape."""
    use_missing = ce.eff_dl is not None
    use_cat = ce.eff_cat is not None

    def fn(ef, et, bv, coh, *rest):
        *opt, Xc = rest
        opt = list(opt)
        dl = opt.pop(0) if use_missing else None
        cn = opt.pop(0) if use_cat else None
        return predict_ops.predict_raw_effective(
            ef, et, bv, coh, Xc,
            max_depth=ce.max_depth, learning_rate=ce.learning_rate,
            base=ce.base_score, n_classes=ce.n_classes_out,
            tree_chunk=ce.tree_chunk, eff_dl=dl,
            missing_bin_value=ce.missing_bin_value, eff_cat=cn,
            use_pallas=False,
        )

    return fn


def lut_predict_fn(tables):
    """The LUT scoring closure (ops/predict_lut.py) over one model's
    quantized tables; `interpret` pinned at EXPORT time — the lowered
    program is platform-specific either way, which the manifest's
    `lut_platforms` records."""
    interpret = jax.default_backend() != "tpu"
    static = dict(
        max_depth=tables.max_depth, learning_rate=tables.learning_rate,
        base=tables.base_score, n_classes=tables.n_classes_out,
        tree_chunk=tables.tree_chunk,
        n_trees_padded=tables.n_trees_padded,
        missing_bin_value=tables.missing_bin_value,
        use_missing=tables.eff_dl is not None,
        use_cat=tables.eff_cat is not None,
        use_scale=tables.leaf_scale is not None,
        interpret=interpret,
    )

    def fn(*args):
        *ops, Xc = args
        return predict_lut.predict_effective_lut_ops(
            tuple(ops), Xc, **static)

    return fn


def lut4_predict_fn(packed):
    """The int4 bit-packed scoring closure (ops/predict_lut.py "int4
    TIER") over one model's PackedTables; `interpret` pinned at EXPORT
    time like the int8 variant."""
    static = dict(packed.static_kwargs(),
                  interpret=jax.default_backend() != "tpu")

    def fn(*args):
        *ops, Xc = args
        return predict_lut.predict_effective_lut4_ops(
            tuple(ops), Xc, **static)

    return fn


def _shape_args(operands, bucket: int, n_features: int) -> list:
    args = [jax.ShapeDtypeStruct(np.asarray(a).shape,
                                 np.asarray(a).dtype) for a in operands]
    args.append(jax.ShapeDtypeStruct((bucket, n_features), jnp.uint8))
    return args


def export_bucket(fn, operands, bucket: int, n_features: int,
                  platforms: tuple | None = None) -> tuple[bytes, tuple]:
    """(serialized StableHLO, platforms actually lowered for) of one
    scoring closure at one bucket shape. Multi-platform lowering is
    best-effort: when it fails (a platform this jax build cannot lower
    for), the export degrades to the current platform and the caller
    records the narrower coverage in the manifest.

    Lowered WITHOUT caller-traceback location metadata
    (jax_traceback_in_locations_limit=0 for the duration): MLIR
    locations embed the EXPORTING call stack's file:line, so the same
    model exported from two different call sites would serialize to
    different bytes — breaking the registry's content addressing (push
    idempotence). The op-level debug payload a serving process never
    reads is exactly the nondeterminism we strip."""
    from jax import export as jax_export

    args = _shape_args(operands, bucket, n_features)
    prev = jax.config.jax_traceback_in_locations_limit
    jax.config.update("jax_traceback_in_locations_limit", 0)
    try:
        if platforms is not None:
            try:
                exp = jax_export.export(jax.jit(fn),
                                        platforms=tuple(platforms))(*args)
                return bytes(exp.serialize()), tuple(exp.platforms)
            except Exception as e:  # ddtlint: disable=broad-except
                # Lowering for a foreign platform is an optional
                # capability (older jax, exotic backends) — fall back to
                # the platform we are actually on rather than failing
                # the export.
                log.warning("multi-platform export for %s failed "
                            "(%s: %s); exporting for %s only", platforms,
                            type(e).__name__, e, jax.default_backend())
        exp = jax_export.export(jax.jit(fn))(*args)
        return bytes(exp.serialize()), tuple(exp.platforms)
    finally:
        jax.config.update("jax_traceback_in_locations_limit", prev)


def deserialize_blob(blob: bytes):
    """Serialized StableHLO -> a callable Exported (the loader jits
    `.call` so each bucket compiles exactly once, at load time)."""
    from jax import export as jax_export

    return jax_export.deserialize(bytearray(blob))


# --------------------------------------------------------------------- #
# QuantizedTables npz round trip (the carried int8 representation)
# --------------------------------------------------------------------- #

_TABLE_SCALARS = ("token", "tree_chunk", "max_depth", "n_classes_out",
                  "learning_rate", "base_score", "loss",
                  "missing_bin_value", "leaf_dtype", "max_abs_err")
_TABLE_ARRAYS = ("eff_feat", "thr_i8", "leaf_q", "leaf_scale", "cls_oh",
                 "eff_dl", "eff_cat")


def tables_to_arrays(tables) -> dict:
    """QuantizedTables -> npz-ready dict (None optionals become empty
    arrays; scalars ride as 0-d numpy)."""
    d = {}
    for k in _TABLE_SCALARS:
        v = getattr(tables, k)
        d[k] = np.bytes_(v.encode()) if isinstance(v, str) else np.asarray(v)
    for k in _TABLE_ARRAYS:
        v = getattr(tables, k)
        d[k] = np.zeros(0, np.int8) if v is None else np.asarray(v)
    return d


def tables_from_arrays(d: dict):
    """Inverse of tables_to_arrays (empty optionals back to None)."""
    kw = {}
    for k in _TABLE_SCALARS:
        v = d[k]
        if np.asarray(v).dtype.kind == "S":
            kw[k] = bytes(np.asarray(v).item()).decode()
        elif k in ("learning_rate", "base_score", "max_abs_err"):
            kw[k] = float(v)
        else:
            kw[k] = int(v)
    for k in _TABLE_ARRAYS:
        a = np.asarray(d[k])
        kw[k] = None if a.size == 0 and k != "cls_oh" else a
    return predict_lut.QuantizedTables(**kw)


# --------------------------------------------------------------------- #
# staging a full servable artifact
# --------------------------------------------------------------------- #

@dataclasses.dataclass
class StagedArtifact:
    stage_dir: str
    manifest: dict
    digest: str          # full sha256 of the manifest bytes


def stage_servable(
    stage_dir: str,
    bundle,                       # api.ModelBundle (or TrainResult-like)
    *,
    buckets: tuple,
    quantize=False,               # False | True/"int8" | "int4"
    raw: bool = False,
    tree_chunk: int = 64,
    run_id: str | None = None,
) -> StagedArtifact:
    """Build a complete servable artifact in `stage_dir` (the registry's
    staging area): model.npz, per-bucket AOT blobs (f32 always, the
    requested LUT tier when `quantize` and that kernel admits the
    shape), lut_tables.npz, and the finalized manifest.json. `quantize`
    is a tier: True/"int8" exports the int8 TreeLUT variant, "int4"
    the bit-packed tier (its tables — leaf_dtype "int4" — ride the same
    lut_tables.npz, token-pinned, so the 4-bit representation survives
    export verbatim). Returns the staged paths + digest;
    `Registry.push(stage_dir, …)` publishes it atomically."""
    from ddt_tpu import api
    from ddt_tpu.serve.engine import normalize_quantize

    ens = bundle.ensemble
    buckets = tuple(sorted({int(b) for b in buckets}))
    if not buckets or buckets[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets}")
    emb = getattr(bundle, "manifest", None) or {}
    if run_id is None:
        run_id = emb.get("run_id")

    os.makedirs(os.path.join(stage_dir, AOT_DIR), exist_ok=True)
    api.save_model(os.path.join(stage_dir, MODEL_FILE), ens,
                   mapper=bundle.mapper,
                   encoder=getattr(bundle, "encoder", None),
                   run_id=run_id)

    ce = ens.compile(tree_chunk=tree_chunk)
    fn = f32_predict_fn(ce)
    operands = ce.arrays()
    F = int(ens.n_features)
    # Manifest coverage is the INTERSECTION across buckets: lowering is
    # per-call best-effort, and a platform the manifest claims must hold
    # for every blob the loader will deserialize.
    platforms: tuple | None = None
    for b in buckets:
        blob, covered = export_bucket(fn, operands, b, F,
                                      platforms=F32_PLATFORMS)
        platforms = covered if platforms is None else tuple(
            p for p in platforms if p in covered)
        with open(os.path.join(stage_dir, AOT_DIR,
                               F32_BLOB.format(bucket=b)), "wb") as f:
            f.write(blob)
    platforms = platforms or ()

    tier = normalize_quantize(quantize)
    quantized_meta = None
    lut_platforms: tuple | None = None
    if tier:
        from ddt_tpu.serve.engine import TIER_LEAF_DTYPE

        tables = ce.quantize(leaf_dtype=TIER_LEAF_DTYPE[tier])
        quantized_meta = {"tier": tier, "leaf_dtype": tables.leaf_dtype,
                          "max_abs_err": tables.max_abs_err}
        # The quantized representation itself rides in the artifact —
        # the TreeLUT fast path survives export even where the lowered
        # kernel blob cannot follow (foreign serving platform).
        from ddt_tpu.utils.atomic import atomic_savez

        atomic_savez(os.path.join(stage_dir, LUT_TABLES_FILE),
                     compressed=True, deterministic=True,
                     **tables_to_arrays(tables))
        on_tpu = jax.default_backend() == "tpu"
        if tier == "int4":
            packed = tables.pack_int4()
            quantized_meta["thr_packed"] = packed.thr_packed
            fits = predict_lut.predict_lut4_fits(
                tables.n_trees_padded, tables.tree_chunk,
                tables.max_depth, F, tables.n_classes_out,
                thr_packed=packed.thr_packed)
            lfn, lops, blob_tpl = (lut4_predict_fn(packed), packed.ops,
                                   LUT4_BLOB)
        else:
            fits = predict_lut.predict_lut_fits(
                tables.n_trees_padded, tables.tree_chunk,
                tables.max_depth, F, tables.n_classes_out)
            lfn, lops, blob_tpl = (lut_predict_fn(tables),
                                   predict_lut.lut_device_operands(
                                       tables), LUT_BLOB)
        if not on_tpu or fits:
            for b in buckets:
                blob, covered = export_bucket(lfn, lops, b, F)
                lut_platforms = covered if lut_platforms is None \
                    else tuple(p for p in lut_platforms if p in covered)
                with open(os.path.join(
                        stage_dir, AOT_DIR,
                        blob_tpl.format(bucket=b)), "wb") as f:
                    f.write(blob)
        else:
            log.warning(
                "%s LUT shape exceeds the kernel's VMEM budget; "
                "artifact carries quantized tables but no lut AOT "
                "blobs", tier)

    # No timestamps: the manifest bytes ARE the artifact digest, and
    # re-exporting the same model must reproduce the same address
    # (push idempotence). pushed_at lives in the registry name index.
    meta = {
        "kind": "servable",
        "model_token": ce.token,
        "loss": ens.loss,
        "n_classes": int(ens.n_classes),
        "n_features": F,
        "n_trees": int(ens.n_trees),
        "max_depth": int(ens.max_depth),
        "tree_chunk": int(tree_chunk),
        "buckets": list(buckets),
        "raw": bool(raw),
        "has_mapper": bundle.mapper is not None,
        "has_encoder": getattr(bundle, "encoder", None) is not None,
        # Drift observatory (ISSUE 19, schema-additive manifest extra):
        # whether the mapper carries a training reference histogram
        # (mapper.ref_counts in model.npz) — the serve tier enables
        # drift scoring iff this is true, and `drift=true` specs can
        # fail fast at load instead of after the first request.
        "drift_reference": getattr(bundle.mapper, "ref_counts",
                                   None) is not None,
        "platforms": list(platforms),
        "lut_platforms": list(lut_platforms or ()),
        "quantized": quantized_meta,
        "run_id": run_id,
        "config_fingerprint": emb.get("config_fingerprint"),
        "git_rev": manifest_mod.git_rev(),
        "jax_version": jax.__version__,
        "export_host_platform": jax.default_backend(),
    }
    digest = manifest_mod.write_artifact_manifest(stage_dir, meta)
    return StagedArtifact(stage_dir=stage_dir,
                          manifest={**meta}, digest=digest)
