// Native CPU SplitGain kernel — the reference's second named kernel
// [BASELINE: "SplitGain"], CPU edition (the TPU edition is ops/split.py).
//
// BIT-PARITY CONTRACT with reference/numpy_trainer.best_splits: float32
// sequential cumsum over bins, float32 gain arithmetic, bfloat16
// round-to-nearest-even rounding of gains before a first-occurrence argmax
// over the flattened (feature, bin) axis. This is what makes the native CPU
// training path grow trees identical to the NumPy oracle and to the TPU
// backend (the repo-wide deterministic-split rule, see ops/split.py).

#include <cmath>
#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

// Round float32 -> bfloat16 (round-to-nearest-even), returned as the
// float32 value the bf16 bits represent. Matches ml_dtypes/XLA semantics
// for finite values; -inf passes through; NaN never reaches this (masked).
inline float to_bf16(float x) {
    uint32_t bits;
    std::memcpy(&bits, &x, 4);
    uint32_t rounded = bits + 0x7FFFu + ((bits >> 16) & 1u);
    rounded &= 0xFFFF0000u;
    float out;
    std::memcpy(&out, &rounded, 4);
    return out;
}

}  // namespace

extern "C" {

void ddt_split_gain(
    const float* hist,        // [n_nodes, F, B, 2] (g, h) sums
    int32_t n_nodes,
    int64_t F,
    int32_t B,
    float reg_lambda,
    float min_child_weight,
    float* best_gain,         // [n_nodes] (bf16-valued float32; -inf if none)
    int32_t* best_feature,    // [n_nodes]
    int32_t* best_bin         // [n_nodes]
) {
    const int64_t fstride = (int64_t)B * 2;
    const int64_t nstride = F * fstride;
    const float NEG_INF = -INFINITY;

#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int32_t n = 0; n < n_nodes; ++n) {
        const float* hn = hist + (int64_t)n * nstride;
        float bg = NEG_INF;
        int64_t bidx = -1;
        for (int64_t f = 0; f < F; ++f) {
            const float* hf = hn + f * fstride;
            // PER-FEATURE totals in np.cumsum's sequential order (twin
            // convention with numpy_trainer/ops-split: feature f's own
            // total makes degenerate complements exactly zero).
            float G = 0.0f, H = 0.0f;
            for (int32_t b = 0; b < B; ++b) {
                G += hf[b * 2 + 0];
                H += hf[b * 2 + 1];
            }
            const float parent = (G * G) / (H + reg_lambda);
            float GL = 0.0f, HL = 0.0f;
            for (int32_t b = 0; b < B - 1; ++b) {  // last bin never valid
                GL += hf[b * 2 + 0];
                HL += hf[b * 2 + 1];
                const float GR = G - GL;
                const float HR = H - HL;
                if (HL < min_child_weight || HR < min_child_weight) continue;
                float gain = 0.5f * (
                    (GL * GL) / (HL + reg_lambda)
                    + (GR * GR) / (HR + reg_lambda)
                    - parent);
                if (std::isnan(gain)) continue;    // 0/0 when reg_lambda == 0
                gain = to_bf16(gain);
                if (gain > bg) {                   // strict >: first index wins
                    bg = gain;
                    bidx = f * B + b;
                }
            }
        }
        best_gain[n] = bg;
        best_feature[n] = bidx < 0 ? 0 : (int32_t)(bidx / B);
        best_bin[n] = bidx < 0 ? 0 : (int32_t)(bidx % B);
    }
}

}  // extern "C"
