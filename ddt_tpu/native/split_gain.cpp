// Native CPU SplitGain kernel — the reference's second named kernel
// [BASELINE: "SplitGain"], CPU edition (the TPU edition is ops/split.py).
//
// BIT-PARITY CONTRACT with reference/numpy_trainer.best_splits: float32
// sequential cumsum over bins, float32 gain arithmetic, bfloat16
// round-to-nearest-even rounding of gains before a first-occurrence argmax
// over the flattened (feature, bin) axis. This is what makes the native CPU
// training path grow trees identical to the NumPy oracle and to the TPU
// backend (the repo-wide deterministic-split rule, see ops/split.py).

#include <cmath>
#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

// Round float32 -> bfloat16 (round-to-nearest-even), returned as the
// float32 value the bf16 bits represent. Matches ml_dtypes/XLA semantics
// for finite values; -inf passes through; NaN never reaches this (masked).
inline float to_bf16(float x) {
    uint32_t bits;
    std::memcpy(&bits, &x, 4);
    uint32_t rounded = bits + 0x7FFFu + ((bits >> 16) & 1u);
    rounded &= 0xFFFF0000u;
    float out;
    std::memcpy(&out, &rounded, 4);
    return out;
}

}  // namespace

extern "C" {

void ddt_split_gain(
    const float* hist,        // [n_nodes, F, B, 2] (g, h) sums
    int32_t n_nodes,
    int64_t F,
    int32_t B,
    float reg_lambda,
    float min_child_weight,
    float* best_gain,         // [n_nodes] (bf16-valued float32; -inf if none)
    int32_t* best_feature,    // [n_nodes]
    int32_t* best_bin         // [n_nodes]
) {
    const int64_t fstride = (int64_t)B * 2;
    const int64_t nstride = F * fstride;
    const float NEG_INF = -INFINITY;

#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int32_t n = 0; n < n_nodes; ++n) {
        const float* hn = hist + (int64_t)n * nstride;
        float bg = NEG_INF;
        int64_t bidx = -1;
        for (int64_t f = 0; f < F; ++f) {
            const float* hf = hn + f * fstride;
            // PER-FEATURE totals in np.cumsum's sequential order (twin
            // convention with numpy_trainer/ops-split: feature f's own
            // total makes degenerate complements exactly zero).
            float G = 0.0f, H = 0.0f;
            for (int32_t b = 0; b < B; ++b) {
                G += hf[b * 2 + 0];
                H += hf[b * 2 + 1];
            }
            const float parent = (G * G) / (H + reg_lambda);
            float GL = 0.0f, HL = 0.0f;
            for (int32_t b = 0; b < B - 1; ++b) {  // last bin never valid
                GL += hf[b * 2 + 0];
                HL += hf[b * 2 + 1];
                const float GR = G - GL;
                const float HR = H - HL;
                if (HL < min_child_weight || HR < min_child_weight) continue;
                float gain = 0.5f * (
                    (GL * GL) / (HL + reg_lambda)
                    + (GR * GR) / (HR + reg_lambda)
                    - parent);
                if (std::isnan(gain)) continue;    // 0/0 when reg_lambda == 0
                gain = to_bf16(gain);
                if (gain > bg) {                   // strict >: first index wins
                    bg = gain;
                    bidx = f * B + b;
                }
            }
        }
        best_gain[n] = bg;
        best_feature[n] = bidx < 0 ? 0 : (int32_t)(bidx / B);
        best_bin[n] = bidx < 0 ? 0 : (int32_t)(bidx % B);
    }
}

// Full-contract SplitGain: feature_mask (colsample), missing_bin (the
// reserved NaN bin B-1 with a learned default direction), cat_mask
// (categorical one-vs-rest, "bin == k goes LEFT"). Bit-parity twin of
// reference/numpy_trainer.best_splits: the argmax runs over the flattened
// [direction(RIGHT first), feature, bin] axis with bf16-rounded gains and
// a strict-> first-occurrence rule, so ties resolve exactly like the
// NumPy oracle and the TPU kernel. feature_mask/cat_mask may be NULL.
void ddt_split_gain_full(
    const float* hist,        // [n_nodes, F, B, 2]
    int32_t n_nodes,
    int64_t F,
    int32_t B,
    float reg_lambda,
    float min_child_weight,
    const uint8_t* feature_mask,   // [F] 1 = allowed, or NULL
    int32_t missing_bin,           // 0/1
    const uint8_t* cat_mask,       // [F] 1 = categorical, or NULL
    float* best_gain,         // [n_nodes] (bf16-valued; -inf if none)
    int32_t* best_feature,
    int32_t* best_bin,
    uint8_t* default_left     // [n_nodes] 1 = NaN rows go LEFT
) {
    const int64_t fstride = (int64_t)B * 2;
    const int64_t nstride = F * fstride;
    const float NEG_INF = -INFINITY;

#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int32_t n = 0; n < n_nodes; ++n) {
        const float* hn = hist + (int64_t)n * nstride;
        float bg = NEG_INF;
        int64_t bidx = -1;       // flattened (dir, f, b); RIGHT block first
        const int n_dirs = missing_bin ? 2 : 1;
        for (int dir = 0; dir < n_dirs; ++dir) {
            for (int64_t f = 0; f < F; ++f) {
                if (feature_mask && !feature_mask[f]) continue;
                const bool is_cat = cat_mask && cat_mask[f];
                if (dir == 1 && is_cat) continue;   // cat: RIGHT block only
                const float* hf = hn + f * fstride;
                // Per-feature totals in sequential f32 order (shared twin
                // convention — see ddt_split_gain above).
                float G = 0.0f, H = 0.0f;
                for (int32_t b = 0; b < B; ++b) {
                    G += hf[b * 2 + 0];
                    H += hf[b * 2 + 1];
                }
                const float parent = (G * G) / (H + reg_lambda);
                // Missing mass (bin B-1) moves LEFT in the dir==1 block.
                const float mg = missing_bin ? hf[(B - 1) * 2 + 0] : 0.0f;
                const float mh = missing_bin ? hf[(B - 1) * 2 + 1] : 0.0f;
                float GLrun = 0.0f, HLrun = 0.0f;
                for (int32_t b = 0; b < B; ++b) {
                    GLrun += hf[b * 2 + 0];
                    HLrun += hf[b * 2 + 1];
                    float GL, HL;
                    if (is_cat) {
                        // One-vs-rest: left child is exactly bin b; every
                        // bin (incl. the last) is a candidate.
                        GL = hf[b * 2 + 0];
                        HL = hf[b * 2 + 1];
                    } else {
                        // Ordinal cumsum; the NaN bin itself (and under
                        // missing, the bin below it) never splits, and
                        // the last bin leaves an empty right child.
                        if (b == B - 1) continue;
                        if (missing_bin && dir == 1 && b == B - 2) continue;
                        GL = GLrun + (dir == 1 ? mg : 0.0f);
                        HL = HLrun + (dir == 1 ? mh : 0.0f);
                    }
                    const float GR = G - GL;
                    const float HR = H - HL;
                    if (HL < min_child_weight || HR < min_child_weight)
                        continue;
                    float gain = 0.5f * (
                        (GL * GL) / (HL + reg_lambda)
                        + (GR * GR) / (HR + reg_lambda)
                        - parent);
                    if (std::isnan(gain)) continue;
                    gain = to_bf16(gain);
                    if (gain > bg) {               // strict >: first wins
                        bg = gain;
                        bidx = ((int64_t)dir * F + f) * B + b;
                    }
                }
            }
        }
        best_gain[n] = bg;
        const int64_t fb = bidx < 0 ? 0 : bidx % (F * B);
        best_feature[n] = bidx < 0 ? 0 : (int32_t)(fb / B);
        best_bin[n] = bidx < 0 ? 0 : (int32_t)(fb % B);
        default_left[n] = bidx >= (int64_t)F * B ? 1 : 0;
    }
}

}  // extern "C"
