// Native CPU reference kernels for the histogram-GBDT hot loop.
//
// The reference ships a compiled CPU implementation of the HistogramBuilder
// and compares device throughput against it (BASELINE.md: ">=5x the repo's
// CPU-reference histogram throughput"). A NumPy np.add.at baseline would be
// dishonestly slow (~1 Mrows/s); this kernel is the real CPU contender the
// TPU path must beat. Built by ddt_tpu/native/Makefile into libddthist.so,
// loaded via ctypes (ddt_tpu/native/__init__.py) — no pybind11 dependency.
//
// Contract identical to ddt_tpu/reference/numpy_trainer.build_histograms:
//   out[node, f, bin, {0,1}] += (g, h) over rows with node_index >= 0.
// out is float32 [n_nodes, F, n_bins, 2], zero-initialised by the caller.
//
// Parallelisation: rows are chunked across OpenMP threads, each thread
// accumulates into a private histogram copy, then copies are reduced. With
// OMP_NUM_THREADS=1 (or no OpenMP) it runs the plain serial loop with no
// allocation overhead.

#include <cstdint>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

void ddt_build_histograms(
    const uint8_t* Xb,         // [R, F] row-major binned features
    const float* g,            // [R]
    const float* h,            // [R]
    const int32_t* node_index, // [R], -1 = frozen (skip row)
    int64_t R,
    int64_t F,
    int32_t n_nodes,
    int32_t n_bins,
    float* out                 // [n_nodes, F, n_bins, 2], pre-zeroed
) {
    const int64_t node_stride = F * (int64_t)n_bins * 2;

#ifdef _OPENMP
    int n_threads = omp_get_max_threads();
#else
    int n_threads = 1;
#endif

    if (n_threads <= 1) {
        for (int64_t r = 0; r < R; ++r) {
            const int32_t n = node_index[r];
            if (n < 0) continue;
            const float gr = g[r];
            const float hr = h[r];
            const uint8_t* row = Xb + r * F;
            float* base = out + (int64_t)n * node_stride;
            for (int64_t f = 0; f < F; ++f) {
                float* cell = base + (f * n_bins + row[f]) * 2;
                cell[0] += gr;
                cell[1] += hr;
            }
        }
        return;
    }

#ifdef _OPENMP
    const int64_t total = (int64_t)n_nodes * node_stride;
    std::vector<std::vector<float>> privs(n_threads);

#pragma omp parallel
    {
        const int t = omp_get_thread_num();
        // Actual team size — can be smaller than omp_get_max_threads()
        // (dynamic adjustment, thread limits); privs[nt..) then stay
        // empty and must not be read by the reduction below.
        const int nt = omp_get_num_threads();
        privs[t].assign(total, 0.0f);
        float* priv = privs[t].data();

#pragma omp for schedule(static)
        for (int64_t r = 0; r < R; ++r) {
            const int32_t n = node_index[r];
            if (n < 0) continue;
            const float gr = g[r];
            const float hr = h[r];
            const uint8_t* row = Xb + r * F;
            float* base = priv + (int64_t)n * node_stride;
            for (int64_t f = 0; f < F; ++f) {
                float* cell = base + (f * n_bins + row[f]) * 2;
                cell[0] += gr;
                cell[1] += hr;
            }
        }

        // Tree-free reduction: each thread owns a disjoint slice of `out`
        // and sums all private copies into it. The cross-thread reads of
        // privs[tt] are ordered by the implicit barrier at the end of the
        // row loop above (every assign + private accumulation
        // happens-before every read here). TSan cannot see that edge when
        // libgomp is uninstrumented and reports these reads as races —
        // the documented false-positive class in native/tsan.supp.
        //
        // Deterministic for a FIXED team size (static chunks, reduction
        // in thread order), but the summation ORDER differs from the
        // serial row-order loop, so multi-thread results differ from the
        // 1-thread/NumPy oracle at the float32 reassociation level
        // (~1e-6 relative). Bit-exactness contracts pin 1 thread via
        // ddt_omp_set_threads.
#pragma omp for schedule(static)
        for (int64_t i = 0; i < total; ++i) {
            float acc = 0.0f;
            for (int tt = 0; tt < nt; ++tt) acc += privs[tt][i];
            out[i] += acc;
        }
    }
#endif
}

// Batch ensemble traversal (CPU reference of the gather+compare predict
// path): complete-heap trees, node <- 2*node+1+(x > thr) unless leaf.
// leaf_out is int32 [T, R] heap slots.
//
// Missing-value support (twin of models/tree._traverse_np): when
// missing_bin_value >= 0, rows whose bin equals it are NaN rows and route
// by default_left[t, n] (1 = left) instead of the threshold compare.
// default_left may be NULL only when missing_bin_value < 0.
// Categorical one-vs-rest (v3): cat_node[t, n] = 1 marks nodes splitting
// "bin == thr goes LEFT" instead of the ordinal compare; NULL = none.
void ddt_traverse_v3(
    const uint8_t* Xb,          // [R, F] binned rows
    const int32_t* feature,     // [T, N] split feature (-1 on leaves)
    const int32_t* thr_bin,     // [T, N]
    const uint8_t* is_leaf,     // [T, N]
    const uint8_t* default_left, // [T, N] or NULL (no missing handling)
    const uint8_t* cat_node,    // [T, N] or NULL (no categorical splits)
    int64_t R,
    int64_t F,
    int64_t T,
    int64_t N,
    int32_t max_depth,
    int32_t missing_bin_value,  // reserved NaN bin id; -1 = disabled
    int32_t* leaf_out           // [T, R]
) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t t = 0; t < T; ++t) {
        const int32_t* feat_t = feature + t * N;
        const int32_t* thr_t = thr_bin + t * N;
        const uint8_t* leaf_t = is_leaf + t * N;
        const uint8_t* dl_t =
            default_left ? default_left + t * N : nullptr;
        const uint8_t* cat_t = cat_node ? cat_node + t * N : nullptr;
        int32_t* out_t = leaf_out + t * R;
        for (int64_t r = 0; r < R; ++r) {
            const uint8_t* row = Xb + r * F;
            int32_t node = 0;
            for (int32_t d = 0; d < max_depth; ++d) {
                if (leaf_t[node]) break;
                const int32_t f = feat_t[node];
                const uint8_t v = row[f];
                int right;
                if (missing_bin_value >= 0 &&
                    v == (uint8_t)missing_bin_value) {
                    right = dl_t && dl_t[node] ? 0 : 1;
                } else if (cat_t && cat_t[node]) {
                    right = v != (uint8_t)thr_t[node] ? 1 : 0;
                } else {
                    right = v > thr_t[node] ? 1 : 0;
                }
                node = 2 * node + 1 + right;
            }
            out_t[r] = node;
        }
    }
}

// OpenMP thread control for callers that need summation-order
// determinism (the multi-thread histogram reduction is deterministic per
// team size but differs from the serial order — see the reduction
// comment above). The bit-exactness tests pin 1 thread around their
// assertions through these.
int32_t ddt_omp_max_threads(void) {
#ifdef _OPENMP
    return omp_get_max_threads();
#else
    return 1;
#endif
}

void ddt_omp_set_threads(int32_t n) {
#ifdef _OPENMP
    if (n > 0) omp_set_num_threads(n);
#else
    (void)n;
#endif
}

}  // extern "C"
