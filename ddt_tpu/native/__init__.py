"""ctypes bindings for the native CPU reference kernels (libddthist.so).

The reference pairs its device kernels with a compiled CPU reference
implementation [BASELINE]; this package is ours — C++ with OpenMP, built by
`make -C ddt_tpu/native` (no pybind11: plain ctypes over an extern-C ABI, per
the environment's binding constraints). On import: load the shared library,
building it on the fly if the toolchain is present; importers (backends/cpu.py)
catch ImportError and fall back to the NumPy oracle kernels.

Exports:
    histogram_native(Xb, g, h, node_index, n_nodes, n_bins) -> np.ndarray
    traverse_native(Xb, feature, thr_bin, is_leaf, max_depth) -> np.ndarray
    split_gain_native(hist, reg_lambda, min_child_weight)
        -> (gain, feature, bin)
    split_gain_full_native(hist, reg_lambda, min_child_weight,
                           feature_mask, missing_bin, cat_mask)
        -> (gain, feature, bin, default_left)   # full oracle contract
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
# DDT_NATIVE_LIB selects an alternate build, e.g. libddthist_asan.so from
# `make -C ddt_tpu/native asan` (run tests under sanitizers; needs the asan
# runtime preloaded — see the Makefile comment).
_SO = os.path.join(_DIR, os.environ.get("DDT_NATIVE_LIB", "libddthist.so"))


# ddt_traverse_v3: the traversal ABI gained default_left/missing_bin
# (v2) then cat_node (v3) params; the version suffix makes a stale
# pre-change .so fail the symbol check below instead of being called with
# a mismatched ABI (which would reinterpret a pointer as the row count).
_SYMBOLS = ("ddt_build_histograms", "ddt_traverse_v3", "ddt_split_gain",
            "ddt_split_gain_full", "ddt_csv_parse", "ddt_omp_max_threads",
            "ddt_omp_set_threads")


def _stale() -> bool:
    """libddthist.so missing or older than any source/Makefile."""
    if not os.path.exists(_SO):
        return True
    so_m = os.path.getmtime(_SO)
    deps = [os.path.join(_DIR, "Makefile")] + [
        os.path.join(_DIR, f) for f in os.listdir(_DIR)
        if f.endswith((".cpp", ".h", ".hpp"))
    ]
    return any(os.path.getmtime(d) > so_m for d in deps if os.path.exists(d))


def _load() -> ctypes.CDLL:
    # Rebuild (BEFORE the first dlopen — dlopen dedupes by path and ctypes
    # never dlcloses, so a post-load rebuild could not be picked up) only
    # when the gitignored .so is missing or older than the sources; a fresh
    # library costs no subprocess on import. An flock serialises concurrent
    # builders (cc writes the .so in place, non-atomically).
    if _stale():
        try:
            import fcntl

            with open(os.path.join(_DIR, ".build.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                if _stale():               # may have been built while waiting
                    subprocess.run(
                        ["make", "-C", _DIR, "-s"], check=True,
                        capture_output=True, timeout=120,
                    )
        except Exception as e:  # toolchain missing / build broke
            if not os.path.exists(_SO):
                raise ImportError(f"native kernel build failed: {e}") from e
            # No toolchain (or unwritable dir) but an existing .so: use it if
            # complete — but say so, because a stale library can be
            # behaviorally outdated in ways the symbol check can't catch,
            # and a parity failure must be traceable here.
            import logging

            logging.getLogger("ddt_tpu.native").warning(
                "native kernel sources are newer than %s but rebuilding "
                "failed (%s); dlopening the STALE library — kernel-parity "
                "failures may stem from this. Run `make -C %s` manually.",
                _SO, e, _DIR,
            )
    lib = ctypes.CDLL(_SO)
    missing = [s for s in _SYMBOLS if not hasattr(lib, s)]
    if missing:
        raise ImportError(
            f"libddthist.so lacks {missing} (stale build, no toolchain to "
            f"refresh it); run `make -C {_DIR} clean libddthist.so`"
        )
    return lib


_lib = _load()

_lib.ddt_build_histograms.argtypes = [
    ctypes.POINTER(ctypes.c_uint8),   # Xb
    ctypes.POINTER(ctypes.c_float),   # g
    ctypes.POINTER(ctypes.c_float),   # h
    ctypes.POINTER(ctypes.c_int32),   # node_index
    ctypes.c_int64,                   # R
    ctypes.c_int64,                   # F
    ctypes.c_int32,                   # n_nodes
    ctypes.c_int32,                   # n_bins
    ctypes.POINTER(ctypes.c_float),   # out
]
_lib.ddt_build_histograms.restype = None

_lib.ddt_traverse_v3.argtypes = [
    ctypes.POINTER(ctypes.c_uint8),   # Xb
    ctypes.POINTER(ctypes.c_int32),   # feature
    ctypes.POINTER(ctypes.c_int32),   # thr_bin
    ctypes.POINTER(ctypes.c_uint8),   # is_leaf
    ctypes.POINTER(ctypes.c_uint8),   # default_left (nullable)
    ctypes.POINTER(ctypes.c_uint8),   # cat_node (nullable)
    ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ctypes.c_int32,                   # max_depth
    ctypes.c_int32,                   # missing_bin_value (-1 = disabled)
    ctypes.POINTER(ctypes.c_int32),
]
_lib.ddt_traverse_v3.restype = None

_lib.ddt_split_gain_full.argtypes = [
    ctypes.POINTER(ctypes.c_float),   # hist
    ctypes.c_int32,                   # n_nodes
    ctypes.c_int64,                   # F
    ctypes.c_int32,                   # B
    ctypes.c_float,                   # reg_lambda
    ctypes.c_float,                   # min_child_weight
    ctypes.POINTER(ctypes.c_uint8),   # feature_mask (nullable)
    ctypes.c_int32,                   # missing_bin
    ctypes.POINTER(ctypes.c_uint8),   # cat_mask (nullable)
    ctypes.POINTER(ctypes.c_float),   # best_gain
    ctypes.POINTER(ctypes.c_int32),   # best_feature
    ctypes.POINTER(ctypes.c_int32),   # best_bin
    ctypes.POINTER(ctypes.c_uint8),   # default_left out
]
_lib.ddt_split_gain_full.restype = None

_lib.ddt_split_gain.argtypes = [
    ctypes.POINTER(ctypes.c_float),   # hist
    ctypes.c_int32,                   # n_nodes
    ctypes.c_int64,                   # F
    ctypes.c_int32,                   # B
    ctypes.c_float,                   # reg_lambda
    ctypes.c_float,                   # min_child_weight
    ctypes.POINTER(ctypes.c_float),   # best_gain
    ctypes.POINTER(ctypes.c_int32),   # best_feature
    ctypes.POINTER(ctypes.c_int32),   # best_bin
]
_lib.ddt_split_gain.restype = None

_lib.ddt_csv_parse.argtypes = [
    ctypes.c_char_p,                  # buf
    ctypes.c_int64,                   # len
    ctypes.c_int64,                   # skip_rows
    ctypes.c_int64,                   # max_rows (-1 = all)
    ctypes.POINTER(ctypes.c_double),  # out (row-major)
    ctypes.c_int64,                   # out capacity in rows
    ctypes.POINTER(ctypes.c_int64),   # n_cols in/out (0 = infer)
    ctypes.c_char_p,                  # err buffer
    ctypes.c_int64,                   # err buffer len
]
_lib.ddt_csv_parse.restype = ctypes.c_int64

_lib.ddt_omp_max_threads.argtypes = []
_lib.ddt_omp_max_threads.restype = ctypes.c_int32
_lib.ddt_omp_set_threads.argtypes = [ctypes.c_int32]
_lib.ddt_omp_set_threads.restype = None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def omp_max_threads() -> int:
    """OpenMP max team size the kernels will use (1 = serial path)."""
    return int(_lib.ddt_omp_max_threads())


def omp_set_threads(n: int) -> None:
    """Pin the kernels' OpenMP team size. The multi-thread histogram
    reduction is deterministic per team size but its summation order
    differs from the serial/NumPy row order (~1e-6 float32 reassociation
    — histogram.cpp reduction comment); bit-exactness contracts pin 1."""
    _lib.ddt_omp_set_threads(int(n))


class omp_threads:
    """Context manager pinning the native kernels' OpenMP team size
    (default 1, the serial bit-exact path); restores the previous size on
    exit even when the body raises."""

    def __init__(self, n: int = 1):
        self._n = n

    def __enter__(self):
        self._prev = omp_max_threads()
        omp_set_threads(self._n)
        return self

    def __exit__(self, *exc):
        omp_set_threads(self._prev)
        return False


def histogram_native(
    Xb: np.ndarray,
    g: np.ndarray,
    h: np.ndarray,
    node_index: np.ndarray,
    n_nodes: int,
    n_bins: int,
) -> np.ndarray:
    """C++ HistogramBuilder; contract of numpy_trainer.build_histograms."""
    R, F = Xb.shape
    Xb = np.ascontiguousarray(Xb, np.uint8)
    g = np.ascontiguousarray(g, np.float32)
    h = np.ascontiguousarray(h, np.float32)
    node_index = np.ascontiguousarray(node_index, np.int32)
    out = np.zeros((n_nodes, F, n_bins, 2), np.float32)
    _lib.ddt_build_histograms(
        _ptr(Xb, ctypes.c_uint8), _ptr(g, ctypes.c_float),
        _ptr(h, ctypes.c_float), _ptr(node_index, ctypes.c_int32),
        R, F, n_nodes, n_bins, _ptr(out, ctypes.c_float),
    )
    return out


def split_gain_native(
    hist: np.ndarray,
    reg_lambda: float,
    min_child_weight: float,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """C++ SplitGain; bit-parity with numpy_trainer.best_splits (same f32
    cumsum order + bf16-rounded deterministic tie-break)."""
    n_nodes, F, B, _ = hist.shape
    hist = np.ascontiguousarray(hist, np.float32)
    gain = np.empty(n_nodes, np.float32)
    feat = np.empty(n_nodes, np.int32)
    bin_ = np.empty(n_nodes, np.int32)
    _lib.ddt_split_gain(
        _ptr(hist, ctypes.c_float), n_nodes, F, B,
        reg_lambda, min_child_weight,
        _ptr(gain, ctypes.c_float), _ptr(feat, ctypes.c_int32),
        _ptr(bin_, ctypes.c_int32),
    )
    return gain, feat, bin_


def split_gain_full_native(
    hist: np.ndarray,
    reg_lambda: float,
    min_child_weight: float,
    feature_mask: np.ndarray | None = None,
    missing_bin: bool = False,
    cat_mask: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """C++ SplitGain, full oracle contract: colsample feature masks, the
    reserved-NaN-bin direction scoring, and categorical one-vs-rest gains.
    Bit-parity with numpy_trainer.best_splits (same flattened
    [direction, feature, bin] bf16 argmax)."""
    n_nodes, F, B, _ = hist.shape
    hist = np.ascontiguousarray(hist, np.float32)
    gain = np.empty(n_nodes, np.float32)
    feat = np.empty(n_nodes, np.int32)
    bin_ = np.empty(n_nodes, np.int32)
    dl = np.empty(n_nodes, np.uint8)
    null_u8 = ctypes.POINTER(ctypes.c_uint8)()
    fm = (np.ascontiguousarray(feature_mask, np.uint8)
          if feature_mask is not None else None)
    cm = (np.ascontiguousarray(cat_mask, np.uint8)
          if cat_mask is not None else None)
    _lib.ddt_split_gain_full(
        _ptr(hist, ctypes.c_float), n_nodes, F, B,
        reg_lambda, min_child_weight,
        _ptr(fm, ctypes.c_uint8) if fm is not None else null_u8,
        1 if missing_bin else 0,
        _ptr(cm, ctypes.c_uint8) if cm is not None else null_u8,
        _ptr(gain, ctypes.c_float), _ptr(feat, ctypes.c_int32),
        _ptr(bin_, ctypes.c_int32), _ptr(dl, ctypes.c_uint8),
    )
    return gain, feat, bin_, dl.astype(bool)


def traverse_native(
    Xb: np.ndarray,
    feature: np.ndarray,
    thr_bin: np.ndarray,
    is_leaf: np.ndarray,
    max_depth: int,
    default_left: np.ndarray | None = None,
    missing_bin_value: int = -1,
    cat_node: np.ndarray | None = None,
) -> np.ndarray:
    """C++ batch tree traversal: leaf heap-slot per (tree, row), int32 [T, R].

    `missing_bin_value` >= 0 enables missing-value routing: rows at that bin
    follow default_left[t, n] instead of the threshold compare (twin of
    models/tree._traverse_np's binned missing path). `cat_node[t, n]` marks
    one-vs-rest nodes ("bin == thr goes left").
    """
    R, F = Xb.shape
    T, N = feature.shape
    Xb = np.ascontiguousarray(Xb, np.uint8)
    feature = np.ascontiguousarray(feature, np.int32)
    thr_bin = np.ascontiguousarray(thr_bin, np.int32)
    leaf8 = np.ascontiguousarray(is_leaf, np.uint8)
    if missing_bin_value >= 0 and default_left is None:
        raise ValueError("missing_bin_value needs default_left")
    null_u8 = ctypes.POINTER(ctypes.c_uint8)()
    dl_ptr = null_u8
    if default_left is not None:
        dl8 = np.ascontiguousarray(default_left, np.uint8)
        dl_ptr = _ptr(dl8, ctypes.c_uint8)
    cat_ptr = null_u8
    if cat_node is not None:
        cat8 = np.ascontiguousarray(cat_node, np.uint8)
        cat_ptr = _ptr(cat8, ctypes.c_uint8)
    out = np.empty((T, R), np.int32)
    _lib.ddt_traverse_v3(
        _ptr(Xb, ctypes.c_uint8), _ptr(feature, ctypes.c_int32),
        _ptr(thr_bin, ctypes.c_int32), _ptr(leaf8, ctypes.c_uint8),
        dl_ptr, cat_ptr,
        R, F, T, N, max_depth, missing_bin_value,
        _ptr(out, ctypes.c_int32),
    )
    return out


def csv_parse_native(
    data: bytes,
    skip_rows: int = 0,
    max_rows: int | None = None,
) -> np.ndarray:
    """Parse an in-memory CSV byte buffer into a float64 [rows, cols]
    matrix (the np.loadtxt(delimiter=",") subset the data layer uses —
    '#' comments, blank lines skipped, strict column-count checking).
    Measured 1.5x np.loadtxt's C tokenizer on this box's single core
    (~130-140 MB/s); rows parse under an OpenMP parallel-for, so many-core
    ingest hosts scale where loadtxt stays single-threaded. Callers fall
    back to np.loadtxt when the native library is unavailable."""
    # Capacity: one row per newline (+1 for a final unterminated line).
    cap = data.count(b"\n") + 1
    # First pass allocation needs n_cols; probe the first data row in
    # Python (cheap) so the buffer can be allocated exactly once.
    n_cols = 0
    pos = 0
    skipped = 0
    while skipped < skip_rows and pos < len(data):
        nl = data.find(b"\n", pos)
        pos = len(data) if nl < 0 else nl + 1
        skipped += 1
    while pos < len(data):
        nl = data.find(b"\n", pos)
        end = len(data) if nl < 0 else nl
        payload = data[pos:end].split(b"#", 1)[0].strip()
        if payload:
            n_cols = payload.count(b",") + 1
            break
        pos = end + 1
    if n_cols == 0:
        return np.empty((0, 0), np.float64)
    out = np.empty((cap, n_cols), np.float64)
    ncols_io = np.array([n_cols], np.int64)
    err = ctypes.create_string_buffer(256)
    rows = _lib.ddt_csv_parse(
        data, len(data), skip_rows,
        -1 if max_rows is None else max_rows,
        _ptr(out, ctypes.c_double), cap,
        ncols_io.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        err, len(err),
    )
    if rows < 0:
        raise ValueError(f"csv parse: {err.value.decode()}")
    return out[:rows]
