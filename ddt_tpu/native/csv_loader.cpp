// Native CSV parser for the data layer (SURVEY.md §2 "Datasets" /
// "Native/C++ components": the loader sits on the hot ingest path for the
// real-data configs — the 2.6 GB Higgs csv). Measured on this box's single
// core: ~130 MB/s single-threaded, 1.5x np.loadtxt's C tokenizer; rows
// parse in an OpenMP parallel-for, so a real many-core ingest host scales
// near-linearly where np.loadtxt stays single-threaded.
//
// Semantics match the np.loadtxt(delimiter=",") subset load_file uses:
//   - physical skip_rows lines consumed first (header handling is done by
//     the Python-side sniffer, which counts physical lines)
//   - '#' starts a comment anywhere in a line; blank/comment-only lines
//     are skipped wherever they appear
//   - every data row must hold exactly n_cols comma-separated doubles
//     (leading/trailing whitespace around tokens tolerated, \r\n line
//     endings tolerated, leading '+' accepted); short/long/malformed rows
//     are an ERROR with the 1-based physical line number reported, never
//     silently dropped
//   - n_cols == 0 on input means "infer from the first data row"
//
// ABI (ctypes, see native/__init__.py):
//   ddt_csv_parse(buf, len, skip_rows, max_rows, out, out_cap_rows,
//                 n_cols_io, err, err_len) -> n_rows (or -1: error in err)
// out is a caller-allocated row-major double buffer of
// out_cap_rows * n_cols doubles (callers size it by counting '\n').

#include <cerrno>
#include <charconv>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <vector>

namespace {

// Floating-point std::from_chars shipped well after the header itself
// (libstdc++ < 11 has only the integer overloads — this build image's
// gcc-10 among them). Feature-tested fallback: strtod_l against a
// pinned "C" locale on a bounded stack copy — locale-INDEPENDENT even
// when the embedding process called setlocale (plain strtod would stop
// at '.' under an LC_NUMERIC=de_DE process), and out-of-range values
// are rejected via ERANGE, matching from_chars' result_out_of_range so
// both build variants parse the same file identically. The copy is
// NUL-terminated and end-checked, preserving the trimmed-span contract.
struct fc_result {
    const char* ptr;
    std::errc ec;
};

inline fc_result parse_double(const char* first, const char* last,
                              double& value) {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
    auto r = std::from_chars(first, last, value);
    return {r.ptr, r.ec};
#else
    static const locale_t c_loc = newlocale(LC_ALL_MASK, "C",
                                            static_cast<locale_t>(nullptr));
    char buf[64];
    size_t n = static_cast<size_t>(last - first);
    if (n == 0 || n >= sizeof(buf)) return {first, std::errc::invalid_argument};
    memcpy(buf, first, n);
    buf[n] = '\0';
    char* endp = nullptr;
    errno = 0;
    value = strtod_l(buf, &endp, c_loc);
    if (endp == buf) return {first, std::errc::invalid_argument};
    if (errno == ERANGE) return {first, std::errc::result_out_of_range};
    return {first + (endp - buf), std::errc()};
#endif
}

// One line's extent [p, q) excluding the terminator; advances *cur past
// the terminator. Returns false at end of buffer.
bool next_line(const char*& cur, const char* end, const char*& p,
               const char*& q) {
    if (cur >= end) return false;
    p = cur;
    const char* nl = static_cast<const char*>(
        memchr(cur, '\n', static_cast<size_t>(end - cur)));
    if (nl == nullptr) {
        q = end;
        cur = end;
    } else {
        q = nl;
        cur = nl + 1;
    }
    if (q > p && q[-1] == '\r') --q;      // \r\n
    return true;
}

// Trim a line to its pre-comment, non-blank payload. Returns false if
// nothing remains (skip the line).
bool payload(const char*& p, const char*& q) {
    if (p >= q) return false;
    const char* hash = static_cast<const char*>(
        memchr(p, '#', static_cast<size_t>(q - p)));
    if (hash != nullptr) q = hash;
    while (p < q && (*p == ' ' || *p == '\t')) ++p;
    while (q > p && (q[-1] == ' ' || q[-1] == '\t')) --q;
    return p < q;
}

struct Line {
    const char* p;
    const char* q;
    long line_no;
};

// Parse one data line's n_cols comma-separated doubles into out_row.
// Returns 0, or writes an error and returns -1. n_cols < 0 = count only
// (first-row inference): writes nothing, returns the column count.
long parse_line(const Line& L, long n_cols, double* out_row, char* err,
                long err_len) {
    long col = 0;
    const char* t = L.p;
    while (true) {
        const char* c = static_cast<const char*>(
            memchr(t, ',', static_cast<size_t>(L.q - t)));
        const char* te = (c == nullptr) ? L.q : c;
        // Trim the token in place (std::from_chars is locale-free and
        // span-based: no copy, no NUL needed — unlike strtod it also
        // rejects leading whitespace, hence the trim).
        const char* ts = t;
        while (ts < te && (*ts == ' ' || *ts == '\t')) ++ts;
        const char* tq = te;
        while (tq > ts && (tq[-1] == ' ' || tq[-1] == '\t')) --tq;
        double v = 0.0;
        if (ts < tq && *ts == '+') ++ts;   // loadtxt accepts leading '+'
        auto res = parse_double(ts, tq, v);
        if (ts == tq || res.ec != std::errc() || res.ptr != tq) {
            snprintf(err, static_cast<size_t>(err_len),
                     "line %ld: empty or unparseable field %ld: '%.32s'",
                     L.line_no, col + 1, (ts < tq) ? ts : "");
            return -1;
        }
        if (n_cols >= 0 && col >= n_cols) {
            snprintf(err, static_cast<size_t>(err_len),
                     "line %ld: more than %ld columns", L.line_no, n_cols);
            return -1;
        }
        if (n_cols >= 0) out_row[col] = v;
        ++col;
        if (c == nullptr) break;
        t = c + 1;
    }
    if (n_cols >= 0 && col != n_cols) {
        snprintf(err, static_cast<size_t>(err_len),
                 "line %ld: %ld columns, expected %ld", L.line_no, col,
                 n_cols);
        return -1;
    }
    return col;
}

}  // namespace

extern "C" {

long ddt_csv_parse(const char* buf, long len, long skip_rows,
                   long max_rows, double* out, long out_cap_rows,
                   long* n_cols_io, char* err, long err_len) {
    const char* cur = buf;
    const char* end = buf + len;
    const char* p;
    const char* q;
    long line_no = 0;
    for (long s = 0; s < skip_rows; ++s) {
        if (!next_line(cur, end, p, q)) break;
        ++line_no;
    }
    // Pass 1 (serial, memchr-speed): index the data lines.
    std::vector<Line> lines;
    lines.reserve(static_cast<size_t>(out_cap_rows));
    while (next_line(cur, end, p, q)) {
        ++line_no;
        if (!payload(p, q)) continue;
        if (max_rows >= 0 && static_cast<long>(lines.size()) >= max_rows)
            break;
        if (static_cast<long>(lines.size()) >= out_cap_rows) {
            snprintf(err, static_cast<size_t>(err_len),
                     "row capacity %ld exceeded", out_cap_rows);
            return -1;
        }
        lines.push_back({p, q, line_no});
    }
    const long rows = static_cast<long>(lines.size());
    if (rows == 0) return 0;
    long n_cols = *n_cols_io;
    if (n_cols == 0) {
        n_cols = parse_line(lines[0], -1, nullptr, err, err_len);
        if (n_cols < 0) return -1;
        *n_cols_io = n_cols;
    }
    // Pass 2: rows are independent — parallel parse. First error (lowest
    // row) wins; the rest of that thread's chunk is abandoned.
    long first_bad = rows;
    char local_err[256];
    local_err[0] = '\0';
#pragma omp parallel for schedule(static) shared(first_bad)
    for (long r = 0; r < rows; ++r) {
        long bad_snapshot;
#pragma omp atomic read
        bad_snapshot = first_bad;
        if (r > bad_snapshot) continue;
        char e[256];
        if (parse_line(lines[static_cast<size_t>(r)], n_cols,
                       out + r * n_cols, e, sizeof(e)) < 0) {
#pragma omp critical
            if (r < first_bad) {
#pragma omp atomic write
                first_bad = r;
                memcpy(local_err, e, sizeof(e));
            }
        }
    }
    if (first_bad < rows) {
        snprintf(err, static_cast<size_t>(err_len), "%s", local_err);
        return -1;
    }
    return rows;
}

}  // extern "C"
