"""Deterministic, config-driven fault injection (the chaos harness).

A FaultPlan is a JSON document (`cfg.fault_plan` / `--fault-plan`)
naming WHICH faults fire WHERE and WHEN:

    {"seed": 0, "faults": [
        {"site": "ckpt.save.between", "round": 4},
        {"site": "stream.chunk_read", "chunk": 1, "times": 2},
        {"site": "multihost.init", "times": 1},
        {"site": "hist.build", "times": 1},
        {"site": "straggler", "device": 1, "delay_ms": 400.0,
         "rounds": [2, 6]}
    ]}

Each entry matches a SITE (the seam catalog below — docs/ROBUSTNESS.md)
plus optional criteria (`round`, `chunk`, `device`, a `rounds`
[lo, hi] window, `after_calls` to skip the first N matching calls)
and fires at most `times` times (default 1) — so a retried seam
sees the fault on attempt 1 and clean I/O on attempt 2, exactly the
transient-fault shape the retry layer exists for. An optional `p`
draws per-call from the plan-seeded RNG (deterministic for a fixed
execution order); without `p` matching is fully deterministic.

Zero overhead when disabled: the seams call the module-level
`inject(site, ...)` / `perturb_ms(site, ...)` functions, whose entire
no-plan path is ONE module-global read (the telemetry disabled-path
discipline; guard-tested in tests/test_robustness.py by making
`FaultPlan.fire` explode while training without a plan).

Every firing emits a `fault` run-log event (kind="injected", site +
context) through the robustness fault sink, so a chaos run's log is
self-describing — which is also how benchwatch knows to exclude
injected-fault artifacts from bench history.
"""

from __future__ import annotations

import dataclasses
import json
import random

# ----------------------------------------------------------------- #
# injected-fault exception types
# ----------------------------------------------------------------- #


class InjectedCrash(RuntimeError):
    """Simulated process death (e.g. a kill between the checkpoint
    pair's two os.replace calls). Deliberately NOT transient: the retry
    layer must never absorb it — the run dies and a later run recovers."""


class InjectedIOError(IOError):
    """Transient I/O fault (stream-chunk read, checkpoint write)."""


class InjectedTimeout(TimeoutError):
    """Bootstrap/RPC timeout (multihost init)."""


class InjectedResourceExhausted(RuntimeError):
    """Device OOM twin: str() carries RESOURCE_EXHAUSTED so the
    histogram degrade ladder treats it exactly like XLA's own
    XlaRuntimeError (is_resource_exhausted matches on the message)."""

    def __init__(self, msg: str = ""):
        super().__init__(f"RESOURCE_EXHAUSTED: injected {msg}".strip())


class InjectedTransient(RuntimeError):
    """Generic transient runtime fault (fetch_tree D2H): str() carries
    UNAVAILABLE so utils.retry.is_transient retries it."""

    def __init__(self, msg: str = ""):
        super().__init__(f"UNAVAILABLE: injected {msg}".strip())


def is_resource_exhausted(e: BaseException) -> bool:
    """Does `e` look like a device allocation failure? Matches XLA's
    XlaRuntimeError("RESOURCE_EXHAUSTED: ...") by message (the class
    lives in jaxlib and moves between versions) and the injected twin."""
    return "RESOURCE_EXHAUSTED" in str(e)


# ----------------------------------------------------------------- #
# the seam catalog: site -> default error kind (None = query site)
# ----------------------------------------------------------------- #
ERRORS = {
    "crash": InjectedCrash,
    "io": InjectedIOError,
    "timeout": InjectedTimeout,
    "resource_exhausted": InjectedResourceExhausted,
    "transient": InjectedTransient,
}

#: The injection sites compiled into the real seams. Raising sites get
#: their default error kind (overridable per entry via "error");
#: "straggler" is a QUERY site — perturb_ms() returns an added delay
#: instead of raising. docs/ROBUSTNESS.md is the narrative catalog.
SITES: dict[str, str | None] = {
    "ckpt.save.write": "io",          # before the ensemble tmp write
    "ckpt.save.between": "crash",     # between the pair's two os.replace
    "ckpt.load": "io",                # checkpoint artifact read
    "stream.chunk_read": "io",        # streaming chunk source read
    "multihost.init": "timeout",      # jax.distributed.initialize
    "hist.build": "resource_exhausted",  # histogram build dispatch
    "fetch_tree": "transient",        # per-tree D2H fetch
    "straggler": None,                # per-partition delay (query)
}

_CRITERIA = ("round", "chunk", "device")


@dataclasses.dataclass
class FaultSpec:
    """One plan entry; `fired`/`calls` are runtime state (a plan
    instance is single-use — load a fresh one per run)."""

    site: str
    times: int = 1
    after_calls: int = 0
    round: int | None = None
    chunk: int | None = None
    device: int | None = None
    rounds: tuple[int, int] | None = None   # inclusive [lo, hi] window
    p: float | None = None
    error: str | None = None
    delay_ms: float = 0.0
    fired: int = 0
    calls: int = 0

    def matches(self, ctx: dict) -> bool:
        for key in _CRITERIA:
            want = getattr(self, key)
            if want is not None and ctx.get(key) != want:
                return False
        if self.rounds is not None:
            r = ctx.get("round")
            if r is None or not (self.rounds[0] <= r <= self.rounds[1]):
                return False
        return True


class FaultPlan:
    """The active plan: ordered FaultSpecs + a seeded RNG for `p` draws.
    `fired_log` records every firing (site, ctx) for test assertions."""

    def __init__(self, specs: list[FaultSpec], seed: int = 0):
        self.specs = specs
        self.seed = seed
        self._rng = random.Random(seed)
        self.fired_log: list[tuple[str, dict]] = []

    def _arm(self, site: str, ctx: dict) -> FaultSpec | None:
        """The first spec for `site` that matches ctx and still has
        firings left (call accounting happens here)."""
        for spec in self.specs:
            if spec.site != site or not spec.matches(ctx):
                continue
            spec.calls += 1
            if spec.fired >= spec.times or spec.calls <= spec.after_calls:
                continue
            if spec.p is not None and self._rng.random() >= spec.p:
                continue
            return spec
        return None

    def fire(self, site: str, **ctx) -> None:
        """Raise the configured fault if a spec matches, else return."""
        spec = self._arm(site, ctx)
        if spec is None:
            return
        spec.fired += 1
        self.fired_log.append((site, dict(ctx)))
        self._emit(site, ctx)
        kind = spec.error or SITES[site] or "crash"
        raise ERRORS[kind](
            f"injected fault at {site} "
            f"({', '.join(f'{k}={v}' for k, v in sorted(ctx.items()))})")

    def delay_ms(self, site: str, **ctx) -> float:
        """Query-site firing: the artificial delay for this call, 0.0
        when no spec matches (the straggler seam)."""
        spec = self._arm(site, ctx)
        if spec is None:
            return 0.0
        spec.fired += 1
        self.fired_log.append((site, dict(ctx)))
        self._emit(site, ctx)
        return float(spec.delay_ms)

    def _emit(self, site: str, ctx: dict) -> None:
        from ddt_tpu.robustness import emit_fault

        emit_fault("injected", site=site, **ctx)


def load_plan(src: "str | dict") -> FaultPlan:
    """FaultPlan from a JSON file path or an already-parsed dict.
    Unknown sites and unknown entry keys fail loudly — a typo'd chaos
    plan silently injecting nothing is worse than an error."""
    if isinstance(src, str):
        with open(src) as f:
            d = json.load(f)
    else:
        d = src
    if not isinstance(d, dict) or "faults" not in d:
        raise ValueError("fault plan must be an object with a 'faults' list")
    known = {f.name for f in dataclasses.fields(FaultSpec)} - {
        "fired", "calls"}
    specs = []
    for i, e in enumerate(d["faults"]):
        if not isinstance(e, dict) or "site" not in e:
            raise ValueError(f"fault entry {i} must be an object with 'site'")
        if e["site"] not in SITES:
            raise ValueError(
                f"fault entry {i}: unknown site {e['site']!r}; "
                f"have {sorted(SITES)}")
        unknown = sorted(set(e) - known)
        if unknown:
            raise ValueError(
                f"fault entry {i} has unknown keys {unknown}; "
                f"valid: {sorted(known)}")
        if e.get("error") is not None and e["error"] not in ERRORS:
            raise ValueError(
                f"fault entry {i}: unknown error kind {e['error']!r}; "
                f"have {sorted(ERRORS)}")
        kw = dict(e)
        if "rounds" in kw and kw["rounds"] is not None:
            lo, hi = kw["rounds"]
            kw["rounds"] = (int(lo), int(hi))
        specs.append(FaultSpec(**kw))
    return FaultPlan(specs, seed=int(d.get("seed", 0)))


# ----------------------------------------------------------------- #
# activation — the telemetry-style zero-overhead global
# ----------------------------------------------------------------- #
_ACTIVE: FaultPlan | None = None


def active_plan() -> FaultPlan | None:
    return _ACTIVE


def activate(plan: FaultPlan | None) -> FaultPlan | None:
    """Install `plan`; returns the previous plan so the caller's
    `finally` can restore it (deactivate)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan
    return prev


def deactivate(prev: FaultPlan | None = None) -> None:
    global _ACTIVE
    _ACTIVE = prev


def inject(site: str, **ctx) -> None:
    """THE seam entry point: raises the configured fault when the active
    plan says so; one global read and a return otherwise."""
    plan = _ACTIVE
    if plan is not None:
        plan.fire(site, **ctx)


def perturb_ms(site: str, **ctx) -> float:
    """Query-seam entry point (straggler delay): 0.0 with no plan."""
    plan = _ACTIVE
    if plan is None:
        return 0.0
    return plan.delay_ms(site, **ctx)
