"""Robustness substrate: fault injection, retry seams, self-healing hooks.

The reference system runs tree construction across a cluster where node
loss and fabric hiccups are routine (SURVEY.md §5 "Failure detection /
elastic recovery"); at the north-star scale a transient fault must cost
a retry, not a run. This package holds the pieces that make recovery a
TESTED property:

- `faultplan` — a seeded, config-driven fault-injection plan
  (`cfg.fault_plan` / `--fault-plan`) that fires named faults at the
  real seams (torn checkpoint write, stream-chunk IOError, multihost
  bootstrap timeout, histogram RESOURCE_EXHAUSTED, straggler delay),
  compiled to a single module-global read when no plan is active.
- `watchdog` — the straggler watchdog consuming the flight recorder's
  per-round partition attribution.
- the process-global FAULT SINK below: deep seams (retry loops, the
  checkpoint fallback, the histogram degrade ladder) emit schema'd
  `fault` events into the active run log without threading a handle
  through every layer. Trainers set it for the duration of a fit; with
  no sink attached emission is one global read and a return.

The retry/backoff engine itself lives in `ddt_tpu.utils.retry` (it is
a utility with no robustness-package dependencies beyond this sink).
Docs: docs/ROBUSTNESS.md.
"""

from __future__ import annotations

# The active fault sink: a telemetry.RunLog (or None). Process-global on
# purpose — same ownership discipline as faultplan's active plan: the
# trainer's fit shim sets it, restores the previous value in `finally`.
_SINK = None


def set_fault_sink(run_log) -> "object | None":
    """Install `run_log` (may be None) as the fault-event sink; returns
    the previous sink so callers can restore it (the activate/deactivate
    pairing every trainer shim uses)."""
    global _SINK
    prev = _SINK
    _SINK = run_log
    return prev


def emit_fault(kind: str, **fields) -> None:
    """Emit a `fault` run-log event through the active sink (no-op when
    none is attached). The event schema requires only `kind`; seams add
    extras (seam, attempt, round, device, ...) — the catalog is the
    fault-kind table in docs/OBSERVABILITY.md."""
    sink = _SINK
    if sink is None:
        return
    sink.emit("fault", kind=kind, **fields)
