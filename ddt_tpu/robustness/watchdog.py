"""Straggler watchdog: act on the flight recorder's partition_skew.

PR 4 made stragglers VISIBLE (per-round `partition_phases` events, the
end-of-run `partition_skew` reduction); this consumes the same per-round
stream and DECIDES: when one device's per-round phase total exceeds the
median of the OTHER lanes by `threshold` for `patience` consecutive
observed rounds, the watchdog flags a repartition request. The Driver acts on it at the
next checkpoint boundary (behind `cfg.straggler_repartition`) by
ROTATING the row-shard → device assignment (TPUDevice.
rotate_row_partitions): shard CONTENTS are untouched — the same global
padded row layout, the same psum structure — so the trained model is
unchanged by construction; only which physical device holds which shard
moves, which is exactly the right response to a slow/thermally-throttled
device and a no-op for pure data skew (documented — data-skew rebalance
needs the elastic rework, ROADMAP item 3).

Signal source: the watchdog observes only where the PartitionRecorder
is active (distributed run WITH a run log) — the probe that produces
per-device times is a barrier the disabled path must never pay, so a
watchdog without telemetry would have nothing to read. Detection alone
(fault events `straggler_detected`) is always on when the recorder is;
the repartition ACTION is behind the config flag.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class StragglerObservation:
    round: int          # 1-based, like every run-log record
    device: int
    skew: float         # max/median of per-device round totals
    streak: int


class StragglerWatchdog:
    """Per-round skew tracker. Feed `observe_round` the recorder's
    flushed {device: {phase: ms}} dict; a non-None return is a
    detection (emit it as a fault event). `pending_repartition` latches
    once the same device straggles `patience` rounds in a row; the
    trainer calls `repartition_done()` after acting."""

    def __init__(self, threshold: float = 2.0, patience: int = 2):
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1.0, got {threshold}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.threshold = threshold
        self.patience = patience
        self._streak = 0
        self._worst: int | None = None
        self.pending_repartition = False
        self.detections: list[StragglerObservation] = []

    def observe_round(self, rnd: int,
                      parts: "dict | None") -> StragglerObservation | None:
        """`rnd` is 0-based (the trainer's loop index); `parts` maps
        device id -> {phase: ms} for one round (or fused block). Returns
        the detection record when the skew threshold trips, else None.
        An empty/absent observation neither extends nor resets the
        streak (no signal is not evidence of balance).

        Skew = slowest lane / median of the OTHER lanes — deliberately
        not partition_skew_summary's max/median-of-all: a median that
        includes the straggler dilutes the signal, and on a 2-lane mesh
        bounds max/median-of-all below 2.0, which would make the default
        threshold unreachable exactly where small meshes need it."""
        if not parts or len(parts) < 2:
            return None
        totals = {dev: sum(ph.values()) for dev, ph in parts.items()}
        worst = max(sorted(totals), key=lambda d: totals[d])
        rest = sorted(v for d, v in totals.items() if d != worst)
        n = len(rest)
        median = rest[n // 2] if n % 2 else (
            rest[n // 2 - 1] + rest[n // 2]) / 2.0
        if median <= 0:
            return None
        skew = totals[worst] / median
        if skew < self.threshold:
            self._streak = 0
            self._worst = None
            return None
        self._streak = self._streak + 1 if worst == self._worst else 1
        self._worst = worst
        obs = StragglerObservation(round=rnd + 1, device=int(worst),
                                   skew=round(skew, 3),
                                   streak=self._streak)
        self.detections.append(obs)
        if self._streak >= self.patience:
            self.pending_repartition = True
        return obs

    def repartition_done(self) -> None:
        self._streak = 0
        self._worst = None
        self.pending_repartition = False


def feed_watchdog(watchdog: "StragglerWatchdog | None", run_log,
                  rnd: int, parts: "dict | None", logger,
                  prefix: str = "") -> "StragglerObservation | None":
    """One round's flushed partition lanes -> watchdog; a detection
    surfaces as a warning on `logger` plus a `straggler_detected` fault
    event in `run_log`. THE shared feed for the Driver's granular and
    fused loops and the streaming device loop (one home, so the event
    fields cannot drift between trainers). Two attribute checks when
    either side is absent."""
    if watchdog is None or parts is None:
        return None
    obs = watchdog.observe_round(rnd, parts)
    if obs is None:
        return None
    logger.warning(
        "%sstraggler detected: device %d at %.2fx the other lanes' "
        "median (round %d, streak %d)", prefix, obs.device, obs.skew,
        obs.round, obs.streak)
    if run_log is not None:
        run_log.emit("fault", kind="straggler_detected", round=obs.round,
                     device=obs.device, skew=obs.skew, streak=obs.streak)
    return obs
