"""Public API (layer L8): ddt.train / ddt.predict.

SURVEY.md §1 L8: "`ddt.train()`, `ddt.predict()`, `python -m ddt_tpu.cli
train --backend=tpu`". Thin orchestration over the layers below: quantize
(L7) → Driver.fit against the flag-selected backend (L5/L4) → TreeEnsemble
(L6); predict routes through the backend's gather+compare scorer.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from ddt_tpu.backends import get_backend
from ddt_tpu.backends.base import DeviceBackend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.quantizer import (BinMapper, feature_bincounts,
                                    fit_bin_mapper)
from ddt_tpu.driver import Driver
from ddt_tpu.models.tree import TreeEnsemble
from ddt_tpu.utils.atomic import atomic_savez

log = logging.getLogger("ddt_tpu.api")


@dataclasses.dataclass
class TrainResult:
    ensemble: TreeEnsemble
    mapper: BinMapper | None      # None when the caller passed binned data
    history: list[dict]           # {round, ms_per_round, train_loss @ log
    #   cadence, valid_<metric> every round when an eval_set was given}
    best_round: int | None = None   # 0-based; set when an eval_set was given
    best_score: float | None = None
    # api.train never fits a categorical encoder itself (it sees only the
    # numeric/pre-encoded matrix); a caller who encoded categorical columns
    # sets this so save() produces a complete artifact.
    encoder: "object | None" = None
    # Provenance stamped into saved artifacts' embedded manifests: the
    # telemetry run_id (present when the run had a run log or capture
    # window — the registry's cross-reference to the training run) and
    # the training config (fingerprinted, never embedded whole).
    run_id: str | None = None
    cfg: TrainConfig | None = None

    def save(self, path: str) -> None:
        """Persist the model artifact: ensemble + bin mapper + categorical
        encoder if one was attached (see the `encoder` field), manifest
        embedded (docs/REGISTRY.md)."""
        save_model(path, self.ensemble, mapper=self.mapper,
                   encoder=self.encoder, run_id=self.run_id, cfg=self.cfg)


@dataclasses.dataclass
class ModelBundle:
    """A loaded model artifact: the ensemble plus the preprocessing state
    (bin mapper, categorical encoder) it was trained with. Scoring new data
    MUST reuse this state — refitting a mapper on the scoring set silently
    produces wrong bins whenever its distribution differs from training
    (round-1 verdict, Weak #2)."""

    ensemble: TreeEnsemble
    mapper: BinMapper | None = None
    encoder: "object | None" = None   # data.categorical.CategoricalEncoder
    # Embedded manifest (schema version, content digest, run_id, git
    # rev — registry/manifest.py), digest-VERIFIED by load_model; None
    # for legacy manifest-less files, which stay loadable.
    manifest: dict | None = None


def save_model(path, ens: TreeEnsemble, mapper: BinMapper | None = None,
               encoder=None, *, run_id: str | None = None,
               cfg: TrainConfig | None = None) -> None:
    """Write one .npz holding the ensemble and, when given, the BinMapper
    and CategoricalEncoder fitted at training time. The file remains loadable
    by plain `TreeEnsemble.load` (extra keys are ignored there).

    An embedded manifest (registry/manifest.py: schema version, content
    digest over every payload array, the training `run_id`, a config
    fingerprint, git rev) rides under the `manifest_json` key —
    load_model verifies the digest so a torn or bit-rotted artifact is
    rejected loudly instead of serving silently wrong trees.

    Written tmp-then-os.replace (the atomic-artifact-write contract,
    docs/ROBUSTNESS.md): a process killed mid-save leaves the previous
    model intact, never a torn npz a serving loader would choke on."""
    from ddt_tpu.registry import manifest as manifest_mod

    d = ens.to_dict()
    if mapper is not None:
        # Reuse the classes' own save() dicts under a key prefix so any
        # future field (e.g. a missing-value bin) flows through here
        # without a second serialization site.
        d.update({f"mapper_{k}": v for k, v in mapper.save().items()})
    if encoder is not None:
        d.update({f"cat_{k}": v for k, v in encoder.save().items()})
    manifest_mod.embed_npz_manifest(
        d, kind="model_bundle", run_id=run_id,
        config_fingerprint=(
            manifest_mod.config_fingerprint_digest(cfg)
            if cfg is not None else None))
    # deterministic: model artifacts are content-addressed by the
    # registry — identical models must produce identical bytes.
    atomic_savez(path, compressed=True, deterministic=True, **d)


def load_model(path, *, verify: bool = True) -> ModelBundle:
    """Load a model artifact written by save_model (or a bare
    TreeEnsemble.save file — mapper/encoder come back None then). When
    the file carries an embedded manifest, its content digest is
    verified — a mismatch raises registry.IntegrityError (a ValueError)
    rather than returning silently corrupt trees; manifest-less legacy
    files load exactly as before. `verify=False` skips the digest pass
    for callers that already proved the file bytes (the registry loader
    restores behind an artifact-level sha256)."""
    from ddt_tpu.registry import manifest as manifest_mod

    with np.load(path) as z:
        d = dict(z)
    manifest = manifest_mod.read_npz_manifest(d, verify=verify,
                                              source=str(path))
    ens = TreeEnsemble.from_dict(d)
    mapper = None
    if "mapper_edges" in d:
        mapper = BinMapper.load(
            {k[len("mapper_"):]: v for k, v in d.items()
             if k.startswith("mapper_")})
    encoder = None
    if "cat_n_cols" in d:
        from ddt_tpu.data.categorical import CategoricalEncoder

        encoder = CategoricalEncoder.load(
            {k[len("cat_"):]: v for k, v in d.items()
             if k.startswith("cat_")})
    return ModelBundle(ensemble=ens, mapper=mapper, encoder=encoder,
                       manifest=manifest)


def train(
    X: np.ndarray,
    y: np.ndarray,
    cfg: TrainConfig | None = None,
    *,
    binned: bool = False,
    mapper: BinMapper | None = None,
    backend: DeviceBackend | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 25,
    log_every: int = 10,
    eval_set: tuple[np.ndarray, np.ndarray] | None = None,
    eval_metric: str | None = None,
    early_stopping_rounds: int | None = None,
    sample_weight: np.ndarray | None = None,
    profile: bool = False,
    run_log=None,
    profiler_window=None,
    status=None,
    **cfg_overrides,
) -> TrainResult:
    """Train a GBDT. `X` is float features (quantized here) unless
    `binned=True` (uint8 bin indices). `cfg_overrides` are TrainConfig fields
    (e.g. train(X, y, n_trees=50, backend="cpu")). `backend` accepts either
    the flag string (a TrainConfig field) or a pre-built DeviceBackend
    instance (e.g. one holding a specific mesh). `run_log` (a JSONL path or
    a telemetry.RunLog) attaches the structured telemetry stream — run
    manifest, per-round records, phase timings, counters, XLA cost
    analysis — rendered by `python -m ddt_tpu.cli report`
    (docs/OBSERVABILITY.md). `profiler_window` (a
    telemetry.profiler.CaptureWindow) captures a programmatic xprof trace
    around a selected round range, cross-referenced into the manifest.
    `status` (a telemetry.statusd.TrainStatus) attaches the live
    training operations plane — the trainer updates it at round
    boundaries and `cli train --status-port` serves it over HTTP; None
    (the default) keeps the trainer statusd-free entirely."""
    if isinstance(backend, str):
        cfg_overrides["backend"] = backend
        backend = None
    if cfg is None:
        cfg = TrainConfig(**cfg_overrides)
    elif cfg_overrides:
        cfg = cfg.replace(**cfg_overrides)

    if binned:
        Xb = np.asarray(X)
        if Xb.dtype != np.uint8:
            raise TypeError("binned=True requires uint8 bin indices")
    else:
        if mapper is None:
            mapper = fit_bin_mapper(np.asarray(X), n_bins=cfg.n_bins,
                                    seed=cfg.seed,
                                    missing_policy=cfg.missing_policy,
                                    cat_features=cfg.cat_features)
        elif cfg.missing_policy == "learn" and not mapper.missing_bin:
            raise ValueError(
                "missing_policy='learn' requires a BinMapper fitted with "
                "the same policy (its top bin must be the NaN bin)"
            )
        if cfg.cat_features:
            # A mapper fitted WITHOUT these columns gave them quantile
            # edges, which merge/permute category ids before the
            # one-vs-rest splits see them — silently wrong models.
            not_identity = mapper.non_identity_columns(cfg.cat_features)
            if not_identity:
                raise ValueError(
                    f"cat_features {not_identity} were not identity-binned "
                    "by this BinMapper; refit it with "
                    f"cat_features={tuple(sorted(cfg.cat_features))} so "
                    "category ids survive binning"
                )
        Xb = mapper.transform(np.asarray(X))
        # Drift reference capture (ISSUE 19): the per-feature bin
        # histogram of the TRAINING matrix, attached to the mapper so it
        # rides the artifact (save_model's mapper_* channel) into the
        # serve tier's divergence scorer. Raw counts — sample size stays
        # visible; the scorer owns normalization. binned=True training
        # has no mapper, so no reference (drift simply stays disabled).
        mapper.ref_counts = feature_bincounts(Xb, mapper.n_bins)

    if eval_set is not None:
        # eval_set binned-ness follows the training data's `binned` flag —
        # never inferred from dtype (raw uint8 features are a real thing).
        Xv, yv = eval_set
        Xv = np.asarray(Xv)
        if binned:
            if Xv.dtype != np.uint8:
                raise TypeError(
                    "training data is pre-binned; eval_set must be uint8 "
                    f"bin indices too, got {Xv.dtype}"
                )
        else:
            Xv = mapper.transform(Xv)
        eval_set = (Xv, np.asarray(yv))

    be = backend if backend is not None else get_backend(cfg)
    driver = Driver(
        be, cfg,
        log_every=log_every,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        profile=profile,
        run_log=run_log,
        profiler_window=profiler_window,
        status=status,
    )
    ens = driver.fit(
        Xb, np.asarray(y),
        eval_set=eval_set,
        eval_metric=eval_metric,
        early_stopping_rounds=early_stopping_rounds,
        sample_weight=sample_weight,
    )
    if mapper is not None:
        from ddt_tpu.reference.numpy_trainer import _fill_raw_thresholds

        _fill_raw_thresholds(ens, mapper)
    return TrainResult(
        ensemble=ens, mapper=mapper, history=driver.history,
        best_round=driver.best_round, best_score=driver.best_score,
        run_id=getattr(driver, "run_id", None), cfg=cfg,
    )


# Memoised row-mesh scoring backends, one per partition count: an explicit
# mesh bypasses the get_backend instance cache, and rebuilding the backend
# per call would discard its compiled-ensemble device cache (the very
# thing the predict overhaul keeps resident).
_ROW_MESH_BACKENDS: dict[int, DeviceBackend] = {}


def _row_mesh_backend(n_partitions: int) -> DeviceBackend:
    be = _ROW_MESH_BACKENDS.get(n_partitions)
    if be is None:
        from ddt_tpu.parallel.mesh import make_row_mesh

        be = get_backend(
            TrainConfig(backend="tpu", n_partitions=n_partitions),
            mesh=make_row_mesh(n_partitions))
        _ROW_MESH_BACKENDS[n_partitions] = be
    return be


def validate_mapper_model(mapper: BinMapper, ens: TreeEnsemble) -> None:
    """The mapper-vs-model scoring contract, ONE home (api.predict per
    call, ServableModel once per model version): the NaN policy must
    match and the model's categorical columns must have been
    identity-binned by this mapper — both failures silently corrupt
    every affected row otherwise. The categorical edge scan is memoized
    on the mapper (BinMapper.non_identity_columns), so repeat calls are
    O(1) — the "binning prologue rebuilt per call even on cache hit"
    fix (ISSUE 8 satellite)."""
    if mapper.missing_bin != ens.missing_bin:
        # A policy mismatch silently misroutes every NaN row (the
        # reserved bin vs bin 0); same guard as train-time.
        raise ValueError(
            f"mapper.missing_bin={mapper.missing_bin} but the "
            f"ensemble was trained with missing_bin="
            f"{ens.missing_bin}; use the training-time mapper "
            "(api.load_model returns it)"
        )
    if ens.has_cat_splits:
        # Same loud-failure contract as missing_bin: the model's
        # categorical columns must have been identity-binned by
        # this mapper or every "bin == k" comparison is garbage.
        not_identity = mapper.non_identity_columns(ens.cat_features)
        if not_identity:
            raise ValueError(
                f"the ensemble splits features {not_identity} "
                "categorically but this BinMapper did not "
                "identity-bin them; use the training-time mapper "
                "(api.load_model returns it)"
            )


def predict(
    ens: "TreeEnsemble | ModelBundle",
    X: np.ndarray,
    *,
    binned: bool = False,
    mapper: BinMapper | None = None,
    raw: bool = False,
    backend: DeviceBackend | None = None,
    cfg: TrainConfig | None = None,
    n_partitions: int | None = None,
) -> np.ndarray:
    """Score a batch. Routes through the device gather+compare path when a
    backend is given (or cfg selects one); NumPy otherwise. A ModelBundle
    (api.load_model's return) is accepted directly — its training-time
    mapper is used unless one is passed explicitly. NOTE: the bundle's
    CategoricalEncoder is NOT applied here (this API never sees which
    columns are categorical-raw — api.train's contract is that callers
    encode); X must carry categorical columns already encoded with
    bundle.encoder.transform, exactly as at training time. The CLI predict
    path does that re-encoding itself.

    `n_partitions > 1` makes multi-chip scoring a FLAG: a 1-D row mesh is
    built via parallel.mesh.make_row_mesh and the batch is row-sharded
    over it — trees replicate, each chip traverses its own rows, no
    collectives (the MULTICHIP dryrun's phase-4 path, now public).
    Ignored when an explicit `backend`/`cfg` already selects one."""
    if n_partitions is not None and n_partitions > 1 \
            and backend is None and cfg is None:
        backend = _row_mesh_backend(n_partitions)
    if isinstance(ens, ModelBundle):
        if mapper is None:
            mapper = ens.mapper
        ens = ens.ensemble
    X = np.asarray(X)
    if not binned:
        if mapper is not None:
            validate_mapper_model(mapper, ens)
            X = mapper.transform(X)
            binned = True
        elif not ens.has_raw_thresholds:
            raise ValueError(
                "predict on raw features needs a mapper or an ensemble with "
                "raw thresholds; or pass binned=True with uint8 bins"
            )
    if backend is None and cfg is not None:
        backend = get_backend(cfg)
    if binned and X.dtype != np.uint8:
        raise TypeError(
            f"binned=True requires uint8 bin indices, got {X.dtype}"
        )
    if backend is not None and binned:
        out = backend.predict_raw(ens, X)
        if raw:
            return out
        # Probability transform on HOST numpy (formula-identical to
        # TreeEnsemble.predict): the old device predict_proba round-trip
        # re-uploaded the fetched [R]-sized scores and dispatched a
        # sigmoid per call — pure prologue cost on every served request,
        # visible as a ddt:predict:upload share drop in `report` now
        # that it is gone (ISSUE 8 satellite).
        from ddt_tpu.utils.metrics import predict_proba_np

        return predict_proba_np(out, ens.loss)
    return ens.predict_raw(X, binned=binned) if raw else ens.predict(
        X, binned=binned
    )
