"""Evaluation metrics (SURVEY.md §5 "Metrics/logging/observability").

The reference's observability story implies per-round train/valid metric
tracking (the standard GBDT trainer surface: LightGBM's `eval_set` /
`early_stopping_rounds`). NumPy implementations — metric evaluation runs on
host over small per-round outputs, never inside the jitted device path.

Each metric takes (y_true, score) where `score` is the model's RAW margin
output (TreeEnsemble.predict_raw): [R] for binary/regression, [R, C] for
softmax. `GREATER_IS_BETTER` drives the early-stopping direction.
"""

from __future__ import annotations

import numpy as np


def _sigmoid(z: np.ndarray) -> np.ndarray:
    # exp(-z) overflows to inf for strongly negative margins; the result
    # (1/inf = 0.0) is exactly right, so suppress the warning rather
    # than switch to a "stable" two-branch form whose ULP differences
    # would break the formula-identity contract with TreeEnsemble.
    # predict (models/tree.py inlines this same expression).
    with np.errstate(over="ignore"):
        return 1.0 / (1.0 + np.exp(-z))


def _softmax(z: np.ndarray) -> np.ndarray:
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def predict_proba_np(raw: np.ndarray, loss: str) -> np.ndarray:
    """Raw margins -> probabilities on HOST numpy, formula-identical to
    TreeEnsemble.predict — the ONE home api.predict and the serving
    tier share. Exists so scoring paths never round-trip an [R]-sized
    score vector back to the device just for a sigmoid (the per-call
    predict prologue fix, ISSUE 8)."""
    raw = np.asarray(raw)
    if loss == "logloss":
        return _sigmoid(raw)
    if loss == "softmax":
        return _softmax(raw)
    return raw


def auc(y_true: np.ndarray, score: np.ndarray) -> float:
    """Binary ROC-AUC via the rank (Mann-Whitney U) formulation, with
    average ranks on ties — matches sklearn.metrics.roc_auc_score."""
    y = np.asarray(y_true).astype(bool).ravel()
    s = np.asarray(score, np.float64).ravel()
    n_pos = int(y.sum())
    n_neg = y.size - n_pos
    if n_pos == 0 or n_neg == 0:
        raise ValueError("AUC needs both classes present")
    order = np.argsort(s, kind="mergesort")
    # average (1-based) rank per tied-score group, fully vectorized — this
    # runs once per boosting round under eval_set, so no Python loops
    s_sorted = s[order]
    is_start = np.empty(y.size, bool)
    is_start[0] = True
    np.not_equal(s_sorted[1:], s_sorted[:-1], out=is_start[1:])
    starts = np.flatnonzero(is_start)
    ends = np.concatenate([starts[1:], [y.size]])
    avg_rank = 0.5 * (starts + ends + 1)            # group average rank
    group_id = np.cumsum(is_start) - 1
    ranks = np.empty(y.size, np.float64)
    ranks[order] = avg_rank[group_id]
    u = ranks[y].sum() - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))


def accuracy(y_true: np.ndarray, score: np.ndarray) -> float:
    y = np.asarray(y_true).ravel()
    s = np.asarray(score)
    pred = s.argmax(axis=1) if s.ndim == 2 else (s > 0).astype(y.dtype)
    return float(np.mean(pred == y))


def rmse(y_true: np.ndarray, score: np.ndarray) -> float:
    y = np.asarray(y_true, np.float64).ravel()
    return float(np.sqrt(np.mean((np.asarray(score, np.float64) - y) ** 2)))


def logloss(y_true: np.ndarray, score: np.ndarray) -> float:
    """Binary or multiclass cross-entropy from raw margins."""
    y = np.asarray(y_true).ravel()
    s = np.asarray(score, np.float64)
    eps = 1e-12
    if s.ndim == 2:
        p = np.clip(_softmax(s), eps, 1.0)
        return float(-np.mean(np.log(p[np.arange(y.size), y.astype(int)])))
    p = np.clip(_sigmoid(s), eps, 1 - eps)
    return float(-np.mean(np.where(y > 0.5, np.log(p), np.log1p(-p))))


METRICS = {
    "auc": auc,
    "accuracy": accuracy,
    "rmse": rmse,
    "logloss": logloss,
}

GREATER_IS_BETTER = {
    "auc": True,
    "accuracy": True,
    "rmse": False,
    "logloss": False,
}


def default_metric(loss: str) -> str:
    """Metric used for eval_set tracking when the caller names none.
    Unknown losses raise ValueError naming the known ones — the same
    error contract as evaluate() (a bare KeyError here used to surface
    as an inscrutable traceback deep inside Driver.fit)."""
    defaults = {"logloss": "logloss", "softmax": "logloss", "mse": "rmse"}
    try:
        return defaults[loss]
    except KeyError:
        raise ValueError(
            f"no default metric for loss {loss!r}; have "
            f"{sorted(defaults)}"
        ) from None


# Score bins for the device AUC twin. 2^16 keeps the within-bin pair
# mass — the ONLY approximation the binned formulation makes — tiny:
# expected same-bin pairs ~ R^2 / (2B), so the absolute AUC error is
# ~ R^2/(2B) * 0.5 / (n_pos * n_neg) ~ 1/B for balanced classes, i.e.
# <= ~2e-5 regardless of validation-set size (tests/test_metrics.py
# measures it adversarially). Counts stay exact in f32 below 2^24
# rows per bin.
DEVICE_AUC_BINS = 1 << 16


def _device_auc():
    """Binned-rank AUC, jittable and psum-distributable (the device twin
    host `auc` never had — without it, choosing auc silently dropped the
    Driver off the ~3x fused dispatch path; round-4 verdict item 3).

    Formulation: scores are min/max-normalised into DEVICE_AUC_BINS
    bins (a monotone map — AUC-invariant up to within-bin ties), class
    histograms are scatter-added and allreduced, and the Mann-Whitney U
    statistic is computed from bin counts with average-rank tie handling
    (within-bin pairs count 1/2) — EXACTLY the host rank formulation
    applied to the binned scores. The U summation runs Kahan-compensated
    over block partials: bin products reach ~2^48 at 10M-row validation
    sets, where a naive f32 running sum loses ~1e-3 relative. Degenerate
    inputs match the host contract in spirit: single-class or empty
    validation data returns NaN (the Driver's NaN-eval guard raises with
    the cause; a jitted twin cannot raise data-dependently), all-equal
    scores return exactly 0.5. Binary only (softmax gets None, like the
    host metric is meaningless there)."""
    import jax
    import jax.numpy as jnp

    B = DEVICE_AUC_BINS

    def kahan_blocked(x):
        # Block partials in f32 (short sums — bounded error), then a
        # Kahan scan over the 256 partials: ~2 eps relative overall.
        s1 = jnp.sum(x.reshape(256, B // 256), axis=1)

        def body(carry, xi):
            s, c = carry
            t = s + (xi - c)
            c = (t - s) - (xi - c)
            return (t, c), None

        (s, _), _ = jax.lax.scan(
            body, (jnp.float32(0), jnp.float32(0)), s1)
        return s

    def fn(y, raw, valid, allreduce=lambda x, op="sum": x):
        if raw.ndim != 1:
            raise ValueError(
                "the device auc twin is binary-only (softmax eval_set "
                "should use logloss/accuracy)")
        m = valid > 0
        mf = m.astype(jnp.float32)
        inf = jnp.float32(jnp.inf)
        lo = allreduce(jnp.min(jnp.where(m, raw, inf)), "min")
        hi = allreduce(jnp.max(jnp.where(m, raw, -inf)), "max")
        span = hi - lo
        scale = jnp.where(span > 0, (B - 1) / span, 0.0)
        idx = jnp.clip(
            jnp.round((raw - lo) * scale).astype(jnp.int32), 0, B - 1)
        posw = mf * (y > 0.5)
        negw = mf * (y <= 0.5)
        pos = allreduce(jnp.zeros(B, jnp.float32).at[idx].add(posw))
        neg = allreduce(jnp.zeros(B, jnp.float32).at[idx].add(negw))
        n_pos = jnp.sum(pos)
        n_neg = jnp.sum(neg)
        cum_neg = jnp.cumsum(neg) - neg          # negatives strictly below
        u = kahan_blocked(pos * (cum_neg + 0.5 * neg))
        denom = n_pos * n_neg
        return jnp.where(denom > 0, u / denom, jnp.float32(jnp.nan))

    return fn


def device_metric(name: str, n_classes: int = 1):
    """jittable twin of a host metric for on-device eval_set scoring:
    (y, raw, valid, allreduce) -> f32 scalar, masked by the pad-row
    validity vector and collective-ready for sharded validation sets
    (`allreduce(x, op)` with op in sum|min|max — psum/pmin/pmax on a
    mesh, identity on one device). Returns None when no twin exists:
    auc with multiclass raw scores (binary auc gets the binned-rank twin
    above — the f32-resolution score seam documented in driver.py widens
    to the binned-auc tolerance there)."""
    if name not in METRICS:
        raise ValueError(f"unknown metric {name!r}; have {sorted(METRICS)}")
    if name == "auc":
        return None if n_classes > 1 else _device_auc()
    import jax
    import jax.numpy as jnp

    def fn(y, raw, valid, allreduce=lambda x, op="sum": x):
        v = valid.astype(jnp.float32)
        n = allreduce(v.sum())
        if name == "accuracy":
            if raw.ndim == 2:
                ok = raw.argmax(axis=1) == y.astype(jnp.int32)
            else:
                ok = (raw > 0) == (y > 0.5)
            return allreduce((ok.astype(jnp.float32) * v).sum()) / n
        yf = y.astype(jnp.float32)
        if name == "rmse":
            d = raw - yf
            return jnp.sqrt(allreduce((d * d * v).sum()) / n)
        # logloss (binary sigmoid / multiclass softmax), host formulas in f32
        if raw.ndim == 2:
            z = raw - raw.max(axis=1, keepdims=True)
            e = jnp.exp(z)
            p = e / e.sum(axis=1, keepdims=True)
            # one-hot select of the true-class probability (no row gather)
            yoh = y.astype(jnp.int32)[:, None] == jnp.arange(
                raw.shape[1], dtype=jnp.int32)[None, :]
            py = jnp.sum(jnp.where(yoh, p, 0.0), axis=1)
            t = -jnp.log(jnp.clip(py, 1e-12, 1.0))
        else:
            p = jnp.clip(jax.nn.sigmoid(raw), 1e-12, 1.0 - 1e-12)
            t = -jnp.where(yf > 0.5, jnp.log(p), jnp.log1p(-p))
        return allreduce((t * v).sum()) / n

    return fn


def evaluate(name: str, y_true: np.ndarray, raw_score: np.ndarray) -> float:
    try:
        fn = METRICS[name]
    except KeyError:
        raise ValueError(
            f"unknown metric {name!r}; have {sorted(METRICS)}"
        ) from None
    return fn(y_true, raw_score)
