"""Atomic npz artifact writes (docs/ROBUSTNESS.md atomic-artifact-write).

THE shared tmp-then-`os.replace` dance for every persistent-artifact
writer (model save, checkpoint ensemble, chunk/cache shards) — one home,
so a future hardening (fsync-before-replace, say) lands once. ddtlint's
`atomic-artifact-write` rule enforces the pattern; this helper is how
the artifact-owning modules comply."""

from __future__ import annotations

import os

import numpy as np


def atomic_savez(path, *, compressed: bool = False, **arrays) -> str:
    """np.savez[_compressed] via a tmp-suffixed sibling + os.replace, so
    a process killed mid-save leaves the previous artifact intact —
    never a torn npz at the canonical name. Mirrors np.savez's
    suffixing (a bare path gains .npz) so the final name matches what a
    direct call produced. Returns the final path; a failed write
    removes its tmp sibling before re-raising."""
    final = str(path)
    if not final.endswith(".npz"):
        final += ".npz"
    tmp = final + ".tmp.npz"
    save = np.savez_compressed if compressed else np.savez
    try:
        save(tmp, **arrays)
        os.replace(tmp, final)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return final
