"""Atomic npz artifact writes (docs/ROBUSTNESS.md atomic-artifact-write).

THE shared tmp-then-`os.replace` dance for every persistent-artifact
writer (model save, checkpoint ensemble, chunk/cache shards) — one home,
so a future hardening (fsync-before-replace, say) lands once. ddtlint's
`atomic-artifact-write` rule enforces the pattern; this helper is how
the artifact-owning modules comply."""

from __future__ import annotations

import os

import numpy as np


def atomic_savez(path, *, compressed: bool = False,
                 deterministic: bool = False, **arrays) -> str:
    """np.savez[_compressed] via a tmp-suffixed sibling + os.replace, so
    a process killed mid-save leaves the previous artifact intact —
    never a torn npz at the canonical name. Mirrors np.savez's
    suffixing (a bare path gains .npz) so the final name matches what a
    direct call produced. Returns the final path; a failed write
    removes its tmp sibling before re-raising.

    `deterministic=True` additionally pins every zip member's mtime to
    the epoch, making the FILE BYTES a pure function of the arrays:
    npz is a zip, and zip stamps each entry with 2-second-resolution
    wall time, so two otherwise-identical saves straddling a tick would
    hash differently — which would break the registry's content
    addressing (same model re-pushed must reuse its digest and version,
    docs/REGISTRY.md). Model artifacts opt in; bulk writers (checkpoint
    cadence, chunk caches) skip the extra rewrite pass."""
    final = str(path)
    if not final.endswith(".npz"):
        final += ".npz"
    tmp = final + ".tmp.npz"
    save = np.savez_compressed if compressed else np.savez
    try:
        save(tmp, **arrays)
        if deterministic:
            _strip_zip_times(tmp)
        os.replace(tmp, final)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return final


def _strip_zip_times(path: str) -> None:
    """Rewrite a zip in place with every member stamped 1980-01-01 (the
    zip epoch) — the one nondeterministic input np.savez bakes into the
    bytes. Entries keep their compression type; the rewrite happens on
    the tmp sibling BEFORE os.replace, so atomicity is untouched."""
    import zipfile

    tmp2 = path + ".tmp.det"
    try:
        with zipfile.ZipFile(path) as src, \
                zipfile.ZipFile(tmp2, "w") as dst:
            for info in src.infolist():
                zi = zipfile.ZipInfo(info.filename,
                                     date_time=(1980, 1, 1, 0, 0, 0))
                zi.compress_type = info.compress_type
                zi.external_attr = info.external_attr
                dst.writestr(zi, src.read(info.filename))
        os.replace(tmp2, path)
    except BaseException:
        try:
            os.remove(tmp2)
        except OSError:
            pass
        raise
