"""Device synchronisation helper.

Measured on this image's tunneled TPU (v5e via the experimental "axon"
platform): jax.block_until_ready() returned after 0.04 ms for a histogram
build whose true device time is ~90 ms (verified by scalar readback — the
same build measured 83–98 ms/iter when each iteration ended with
float(jnp.sum(out))). The relay evidently acknowledges enqueue, not
completion, so block_until_ready is NOT a barrier here. Every timing/sync
point in this repo therefore funnels through device_sync(): a scalar-reduce
readback, which cannot return before the producing program has executed.
Device programs execute in submission order, so syncing on the last output
of a sequence fences the whole sequence.
"""

from __future__ import annotations

import jax.numpy as jnp


def device_sync(x) -> float:
    """True device barrier: reduce `x` to a scalar and fetch it."""
    return float(jnp.sum(x))
