"""Tracing/profiling (SURVEY.md §5): phase breakdown + XLA profiler capture.

Two layers:
- PhaseTimer: lightweight host-side wallclock breakdown of the training
  phases the reference cares about (hist / allreduce / gain / predict). On
  TPU each phase must end with a device sync to be meaningful — pass
  utils/device.device_sync (bound to the phase's output) as the `sync`
  callable; see that module for why block_until_ready is not a barrier on
  this platform.
- trace(): context manager around jax.profiler.trace producing a
  TensorBoard/Perfetto trace directory with Pallas kernel timelines.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Callable


class PhaseTimer:
    """Accumulate wallclock per named phase; report ms + share."""

    def __init__(self, sync: Callable | None = None):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self._sync = sync

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        yield
        if self._sync is not None:
            self._sync()
        self.totals[name] += time.perf_counter() - t0
        self.counts[name] += 1

    def as_json(self) -> list[dict]:
        """Stable JSON form of the phase breakdown, embedded verbatim in
        the telemetry run log's `phase_timings.phases` field
        (docs/OBSERVABILITY.md). The keys — phase, ms_total, ms_per_call,
        calls, share — are a COMPATIBILITY CONTRACT with external log
        consumers; extend, never rename."""
        total = sum(self.totals.values()) or 1.0
        return [
            {
                "phase": k,
                "ms_total": round(v * 1e3, 2),
                "ms_per_call": round(v * 1e3 / max(1, self.counts[k]), 3),
                "calls": self.counts[k],
                "share": round(v / total, 3),
            }
            for k, v in sorted(
                self.totals.items(), key=lambda kv: -kv[1]
            )
        ]

    def report(self) -> list[dict]:
        """Human-consumption twin of as_json() (same records; kept as the
        logging-oriented name the Driver has always exposed)."""
        return self.as_json()

    def log_report(self, logger) -> None:
        """The INFO-level phase table — one formatting home for every
        trainer that prints a breakdown (Driver, fit_streaming)."""
        for rec in self.as_json():
            logger.info("phase %-12s %8.2f ms total  %7.3f ms/call  "
                        "x%-5d %5.1f%%", rec["phase"], rec["ms_total"],
                        rec["ms_per_call"], rec["calls"],
                        100 * rec["share"])


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler capture: `with trace("/tmp/prof"): step()` then open in
    TensorBoard (or xprof) — shows XLA op + Pallas kernel timelines."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
