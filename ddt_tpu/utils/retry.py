"""Jittered exponential backoff with a deadline, for host-loss-prone seams.

The seams this wraps (stream chunk read, checkpoint save/load, multihost
bootstrap, the per-tree D2H fetch) share one failure shape: a transient
environmental fault — NFS blip, preempted peer, tunnel reset — that a
second attempt moments later survives. The engine is deliberately dumb:
classify (is_transient), back off exponentially with DETERMINISTICALLY
seeded jitter (no wall-clock entropy — chaos runs must replay), respect
a wall-clock deadline, and tell the run log about every attempt
(schema'd `fault` events kind="retry" through the robustness fault
sink, plus the `fault_retries` counter), so recovery is attributable,
never silent.

Hot-path discipline: the FIRST attempt is an inline call inside a bare
try — the no-fault path pays one frame and no allocation, and everything
slower lives in `_backoff_loop`, which the zero-overhead guard test
explodes to prove a clean run never enters it (the telemetry
disabled-path bar).

Clock and sleep are injectable for the fake-clock unit tests
(tests/test_robustness.py: deadline enforcement, jitter bounds, event
emission)."""

from __future__ import annotations

import dataclasses
import errno
import logging
import random
import time
import zlib

from ddt_tpu.robustness import emit_fault
from ddt_tpu.telemetry import counters as tele_counters

log = logging.getLogger("ddt_tpu.retry")

#: Exception types retried by default. TimeoutError/ConnectionError are
#: OSError subclasses but named for the reader.
TRANSIENT_TYPES = (IOError, OSError, TimeoutError, ConnectionError)
#: Runtime-error messages that mark a transient fabric/runtime fault
#: (jaxlib's XlaRuntimeError hierarchy moves between versions; the
#: status-code prefix in the message is the stable surface).
TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED")
#: OSError errnos that mark a PERMANENT condition — a missing file or a
#: bad path does not heal on attempt 2, so backing off only delays and
#: dresses up a misconfiguration as transient-fault recovery.
PERMANENT_ERRNOS = frozenset({
    errno.ENOENT, errno.EACCES, errno.EPERM, errno.EISDIR, errno.ENOTDIR,
    errno.EEXIST, errno.ENAMETOOLONG, errno.EROFS, errno.ENOSPC,
})


def is_transient(e: BaseException) -> bool:
    """Default retryability: transient I/O and fabric faults only.
    Permanent filesystem errors (ENOENT, EACCES, ... — a mis-named chunk
    file fails identically forever) surface immediately;
    RESOURCE_EXHAUSTED is deliberately NOT transient (the same shape
    OOMs again — that is the degrade ladder's job, backends/tpu.py),
    and InjectedCrash (a simulated process death) never retries."""
    if isinstance(e, TRANSIENT_TYPES):
        return getattr(e, "errno", None) not in PERMANENT_ERRNOS
    msg = str(e)
    return any(m in msg for m in TRANSIENT_MARKERS)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """attempts is the TOTAL try count (first call included). Each
    backoff delay is base_s * multiplier^(attempt-1), jittered DOWN into
    [delay * (1 - jitter), delay] — full delays never stretch, so the
    deadline bound is exact. deadline_s caps elapsed-time-plus-next-
    sleep: the engine gives up rather than start a sleep it knows
    overruns the budget."""

    attempts: int = 4
    base_s: float = 0.05
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: float = 30.0


DEFAULT_POLICY = RetryPolicy()


def retry_call(fn, *args, seam: str, policy: RetryPolicy | None = None,
               retryable=is_transient, clock=time.monotonic,
               sleep=time.sleep, rng: "random.Random | None" = None,
               **kwargs):
    """Call fn(*args, **kwargs), retrying transient failures per
    `policy`. `seam` names the call site in fault events and logs."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:
        if not retryable(e):
            raise
        return _backoff_loop(fn, args, kwargs, seam,
                             policy or DEFAULT_POLICY, retryable, e,
                             clock, sleep, rng)


def _backoff_loop(fn, args, kwargs, seam, policy, retryable, first_error,
                  clock, sleep, rng):
    """The slow path — entered only after a retryable failure (the
    zero-overhead guard test monkeypatches this to explode)."""
    if rng is None:
        # Seeded from the seam NAME only (zlib.crc32 — stable across
        # processes, unlike str hash()), so a replayed chaos run draws
        # the identical jitter sequence.
        rng = random.Random(zlib.crc32(seam.encode()))
    t0 = clock()
    err = first_error
    attempt = 1
    while True:
        tele_counters.record_fault_retry()
        emit_fault("retry", seam=seam, attempt=attempt,
                   error=type(err).__name__, message=str(err)[:200])
        log.warning("retry[%s]: attempt %d/%d failed: %s",
                    seam, attempt, policy.attempts, err)
        if attempt >= policy.attempts:
            emit_fault("retry_exhausted", seam=seam, attempt=attempt,
                       error=type(err).__name__)
            raise err
        delay = policy.base_s * policy.multiplier ** (attempt - 1)
        delay *= 1.0 - policy.jitter * rng.random()
        if clock() - t0 + delay > policy.deadline_s:
            emit_fault("retry_deadline", seam=seam, attempt=attempt,
                       error=type(err).__name__,
                       deadline_s=policy.deadline_s)
            raise err
        sleep(delay)
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # classify-and-loop, never swallow
            if not retryable(e):
                raise
            err = e


def retrying_chunk_fn(chunk_fn, policy: RetryPolicy | None = None):
    """Wrap a streaming chunk source (fit_streaming's ChunkFn contract)
    so every read — full chunks AND the label-only side channel —
    retries transient I/O faults, with the `stream.chunk_read`
    injection seam INSIDE the retried callable (an injected IOError on
    attempt 1 is retried like a real one; the plan's `times` budget
    makes attempt 2 clean). Side-channel attributes (n_features,
    n_chunks, binned, labels) are preserved — chunk sources are pure,
    so a retried re-read returns identical data by contract."""
    from ddt_tpu.robustness import faultplan

    def read(c: int):
        faultplan.inject("stream.chunk_read", chunk=c)
        return chunk_fn(c)

    def f(c: int):
        return retry_call(read, c, seam="stream.chunk_read",
                          policy=policy)

    for attr in ("n_features", "n_chunks", "binned"):
        if hasattr(chunk_fn, attr):
            setattr(f, attr, getattr(chunk_fn, attr))
    labels = getattr(chunk_fn, "labels", None)
    if labels is not None:
        def read_labels(c: int):
            faultplan.inject("stream.chunk_read", chunk=c)
            return labels(c)

        f.labels = lambda c: retry_call(
            read_labels, c, seam="stream.chunk_read", policy=policy)
    if getattr(chunk_fn, "host_sharded", False):
        # Host-sharded sources (data.chunks.HostShardedChunks): the
        # per-part X reads go through the SAME retry seam; ownership
        # bookkeeping (owned_slots / rotate_assignment / row counts)
        # passes through to the live source object so an assignment
        # rotation is visible to every holder of this wrapper.
        f.host_sharded = True
        f.n_shards_per_chunk = chunk_fn.n_shards_per_chunk
        f.owned_slots = chunk_fn.owned_slots
        f.rotate_assignment = chunk_fn.rotate_assignment
        f.part_rows = chunk_fn.part_rows
        f.chunk_rows = chunk_fn.chunk_rows

        def read_part(c: int, s: int):
            faultplan.inject("stream.chunk_read", chunk=c)
            return chunk_fn.read_part(c, s)

        f.read_part = lambda c, s: retry_call(
            read_part, c, s, seam="stream.chunk_read", policy=policy)
    return f
