"""Checkpoint/resume (SURVEY.md §5): per-tree restartable training.

A GBDT ensemble is tiny (KBs–MBs of node arrays), so checkpointing is simply:
after every K boosting rounds, atomically write the partial ensemble + a
cursor (completed rounds, config fingerprint, ensemble content digest).
Resume = load node arrays into the pre-allocated ensemble, rescore the
partial ensemble to rebuild the boosting state (Driver does that part), and
continue the loop. Exactly restartable because training is deterministic
given the binned data (SURVEY.md §5 "checkpoint/resume"); the fault-injection
test kills a training process mid-run and verifies the resumed ensemble
matches an uninterrupted one (tests/test_faultinject.py).

Hardening (docs/ROBUSTNESS.md):

- **Pair atomicity via digest.** ensemble.npz and cursor.json are two
  separate os.replace's; a crash BETWEEN them leaves a new ensemble beside a
  stale cursor. The cursor therefore carries the sha256 of the ensemble file
  it describes — resume validates the pair and a mismatch is a detected torn
  write, never a silently skewed resume.
- **Keep-last-k history.** After each top-level pair lands, it is hard-linked
  (copy fallback) into `ckpt-<round>/`; the newest `keep_last` rounds are
  retained. A torn or corrupt top-level pair falls back to the newest VALID
  history pair instead of crashing. (Links share inodes: a torn REWRITE —
  always a new file via os.replace — never touches history, while in-place
  bit rot on the latest pair also hits the history entry sharing its inode;
  the fallback then recovers one save older, which digest validation finds
  on its own.)
- **Corruption = no checkpoint, not a crash.** A truncated cursor.json, an
  unreadable npz, or a digest mismatch logs a warning, emits a `fault` event
  (kind checkpoint_corrupt / checkpoint_fallback), and resumes from the best
  surviving pair — or returns 0 (fresh start) when nothing survives.
  An INCOMPATIBLE-but-valid checkpoint still raises: that is a user error
  (wrong directory), and resuming it would corrupt the run silently.
- **Retry seams.** The artifact writes/reads retry transient I/O faults with
  backoff (utils/retry.py seams ckpt.save / ckpt.load), and the chaos
  harness's injection sites (ckpt.save.write, ckpt.save.between, ckpt.load —
  robustness/faultplan.py) sit at the real failure points.

The resumed == uninterrupted bit-identity contract is unchanged: the cursor
and node-array semantics are exactly the pre-hardening ones, old cursors
without a digest remain resumable, and history retention never rewrites the
top-level pair the existing tests poll."""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import re
import shutil
import zipfile

from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble
from ddt_tpu.robustness import emit_fault, faultplan
from ddt_tpu.utils import retry
from ddt_tpu.utils.atomic import atomic_savez

log = logging.getLogger("ddt_tpu.checkpoint")

CKPT_FILE = "ensemble.npz"
CURSOR_FILE = "cursor.json"
HISTORY_PREFIX = "ckpt-"
_HISTORY_RE = re.compile(re.escape(HISTORY_PREFIX) + r"(\d+)$")
#: retained `ckpt-<round>` history pairs (beyond the top-level pair)
KEEP_LAST = 3

#: cursor fields resume can trust only when present (old checkpoints
#: predate them and stay resumable): ensemble_digest (pair validation).
CURSOR_SCHEMA = 2


def _cfg_fingerprint(cfg: TrainConfig) -> dict:
    """The config fields that must match for a checkpoint to be resumable."""
    d = dataclasses.asdict(cfg)
    # System knobs may legitimately differ across resume (e.g. resume on a
    # different partition count — distribution never changes results), and
    # n_trees may grow (resuming to train further is the point of resuming).
    # The robustness knobs are system knobs too: a run that crashed UNDER a
    # fault plan must resume WITHOUT one.
    for k in ("n_trees", "n_partitions", "feature_partitions",
              "host_partitions", "mesh_shape", "hist_impl", "backend",
              "matmul_input_dtype", "fault_plan", "straggler_repartition",
              "straggler_skew_threshold"):
        d.pop(k, None)
    # JSON round-trips tuples as lists; normalize so a saved fingerprint
    # compares equal to a freshly computed one.
    d["cat_features"] = list(d.get("cat_features", ()))
    return d


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _history_dirs(ckpt_dir: str) -> list[tuple[int, str]]:
    """(round, path) of every ckpt-<round> history dir, newest first."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    for name in names:
        m = _HISTORY_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    out.sort(reverse=True)
    return out


def _link_or_copy(src: str, dst: str) -> None:
    """Hard-link (same-filesystem free) with a copy fallback (EXDEV,
    filesystems without links). os.replace'ing the source later leaves
    the linked inode untouched — which is exactly why history retention
    costs no second serialization."""
    if os.path.exists(dst):
        os.remove(dst)
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


def _retain_history(ckpt_dir: str, completed_rounds: int,
                    keep_last: int) -> None:
    """Link the just-landed top-level pair into ckpt-<round>/ and prune
    older history past `keep_last`. Best-effort by design: a failure
    here must never fail the save that already landed."""
    hist = os.path.join(ckpt_dir, f"{HISTORY_PREFIX}{completed_rounds:06d}")
    try:
        os.makedirs(hist, exist_ok=True)
        _link_or_copy(os.path.join(ckpt_dir, CKPT_FILE),
                      os.path.join(hist, CKPT_FILE))
        _link_or_copy(os.path.join(ckpt_dir, CURSOR_FILE),
                      os.path.join(hist, CURSOR_FILE))
    except OSError as e:
        log.warning("checkpoint history retention failed for round %d: %s",
                    completed_rounds, e)
        return
    for _, path in _history_dirs(ckpt_dir)[keep_last:]:
        shutil.rmtree(path, ignore_errors=True)


def save_checkpoint(
    ckpt_dir: str, ens: TreeEnsemble, cfg: TrainConfig,
    completed_rounds: int, keep_last: int = KEEP_LAST,
) -> None:
    """Atomically persist the ensemble + cursor after `completed_rounds`,
    then retain the pair in the keep-last-k history."""
    os.makedirs(ckpt_dir, exist_ok=True)
    final = os.path.join(ckpt_dir, CKPT_FILE)

    def _write_ensemble() -> str:
        faultplan.inject("ckpt.save.write", round=completed_rounds)
        atomic_savez(final, compressed=True, **ens.to_dict())
        return _sha256_file(final)

    digest = retry.retry_call(_write_ensemble, seam="ckpt.save")
    # The pair-atomicity gap under test: a crash HERE leaves ensemble.npz
    # one save ahead of cursor.json — the digest below is how resume
    # detects it (tests/test_robustness.py, scripts/chaos_smoke.py).
    faultplan.inject("ckpt.save.between", round=completed_rounds)
    cur = {
        "completed_rounds": int(completed_rounds),
        "config": _cfg_fingerprint(cfg),
        "ensemble_digest": digest,
        "ckpt_schema": CURSOR_SCHEMA,
    }

    def _write_cursor() -> None:
        tmp_c = os.path.join(ckpt_dir, CURSOR_FILE + ".tmp")
        with open(tmp_c, "w") as f:
            json.dump(cur, f)
        os.replace(tmp_c, os.path.join(ckpt_dir, CURSOR_FILE))

    retry.retry_call(_write_cursor, seam="ckpt.save")
    _retain_history(ckpt_dir, completed_rounds, keep_last)


def maybe_save(
    ckpt_dir: str | None,
    ens: TreeEnsemble,
    cfg: TrainConfig,
    completed_rounds: int,
    every: int | None = None,
) -> None:
    """save_checkpoint when a directory is configured and either `every`
    is None (forced — the end-of-training save) or `completed_rounds`
    hits the cadence. The single home of the save policy for the Driver
    and the streaming trainer."""
    if ckpt_dir is None:
        return
    if every is not None and completed_rounds % every != 0:
        return
    save_checkpoint(ckpt_dir, ens, cfg, completed_rounds)


def _read_pair(d: str) -> "dict | str | None":
    """Load the (cursor, ensemble) pair in directory `d`.

    Returns the loaded {"rounds", "cur", "saved"} dict when the pair is
    present AND internally consistent; a string REASON when something is
    there but torn/corrupt (truncated JSON, unreadable npz, digest
    mismatch); None when the pair is simply absent."""
    cursor_path = os.path.join(d, CURSOR_FILE)
    ckpt_path = os.path.join(d, CKPT_FILE)
    have_cursor = os.path.exists(cursor_path)
    have_ckpt = os.path.exists(ckpt_path)
    if not have_cursor and not have_ckpt:
        return None
    if not have_cursor:
        return f"{CKPT_FILE} present but {CURSOR_FILE} missing"
    if not have_ckpt:
        return f"{CURSOR_FILE} present but {CKPT_FILE} missing"

    def _read_cursor():
        faultplan.inject("ckpt.load")
        with open(cursor_path) as f:
            return json.load(f)

    try:
        cur = retry.retry_call(_read_cursor, seam="ckpt.load")
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        return f"{CURSOR_FILE} unreadable: {type(e).__name__}: {e}"
    if not isinstance(cur, dict) or "completed_rounds" not in cur \
            or "config" not in cur:
        return f"{CURSOR_FILE} malformed (missing required fields)"
    digest = cur.get("ensemble_digest")
    if digest is not None:
        try:
            actual = _sha256_file(ckpt_path)
        except OSError as e:
            return f"{CKPT_FILE} unreadable: {e}"
        if actual != digest:
            return (f"{CKPT_FILE} does not match the cursor's digest "
                    "(torn checkpoint write)")
    try:
        saved = retry.retry_call(TreeEnsemble.load, ckpt_path,
                                 seam="ckpt.load")
    except (OSError, ValueError, KeyError, EOFError,
            zipfile.BadZipFile) as e:
        return f"{CKPT_FILE} unreadable: {type(e).__name__}: {e}"
    return {"rounds": int(cur["completed_rounds"]), "cur": cur,
            "saved": saved}


def try_resume(ckpt_dir: str, ens: TreeEnsemble, cfg: TrainConfig,
               run_log=None) -> int:
    """Load a checkpoint into `ens` (in place). Returns completed rounds
    (0 = nothing to resume). Raises if a VALID checkpoint's config is
    incompatible — resuming a different run would corrupt it silently.

    Torn/corrupt artifacts never raise: the top-level pair is validated
    (cursor parse, ensemble digest, npz load) and on failure resume
    FALLS BACK to the newest valid `ckpt-<round>` history pair, emitting
    `fault` events (checkpoint_corrupt per bad candidate,
    checkpoint_fallback on recovery) into `run_log` (and the process
    fault sink); with no survivor it returns 0 with a warning — a
    damaged checkpoint directory costs recomputation, not the run."""
    def _fault(kind: str, **fields) -> None:
        if run_log is not None:
            run_log.emit("fault", kind=kind, **fields)
        else:
            emit_fault(kind, **fields)

    candidates = [("latest", ckpt_dir)] + [
        (f"{HISTORY_PREFIX}{r:06d}", p) for r, p in _history_dirs(ckpt_dir)]
    saw_corrupt = False
    for label, d in candidates:
        res = _read_pair(d)
        if res is None:
            continue
        if isinstance(res, str):
            log.warning("checkpoint %s (%s): %s — trying older history",
                        label, d, res)
            _fault("checkpoint_corrupt", candidate=label, reason=res)
            saw_corrupt = True
            continue
        cur, saved, rounds = res["cur"], res["saved"], res["rounds"]
        # Fingerprint fields added over time default to their empty value
        # so checkpoints written before a field existed stay resumable.
        cur["config"].setdefault("cat_features", [])
        if cur["config"] != _cfg_fingerprint(cfg):
            raise ValueError(
                f"checkpoint at {ckpt_dir} was written by an incompatible "
                "config; refusing to resume. Delete the directory to start "
                "fresh."
            )
        if rounds > cfg.n_trees:
            raise ValueError(
                f"checkpoint at {ckpt_dir} has {rounds} completed rounds "
                f"but cfg.n_trees={cfg.n_trees}; raise n_trees to resume "
                "(a finished checkpoint cannot be shrunk in place)."
            )
        if saw_corrupt:
            log.warning("checkpoint fallback: resuming from %s at round %d",
                        label, rounds)
            _fault("checkpoint_fallback", candidate=label, round=rounds)
        C = cfg.n_classes if cfg.loss == "softmax" else 1
        k = rounds * C
        ens.feature[:k] = saved.feature[:k]
        ens.threshold_bin[:k] = saved.threshold_bin[:k]
        ens.threshold_raw[:k] = saved.threshold_raw[:k]
        ens.is_leaf[:k] = saved.is_leaf[:k]
        ens.leaf_value[:k] = saved.leaf_value[:k]
        ens.split_gain[:k] = saved.split_gain[:k]
        ens.default_left[:k] = saved.default_left[:k]
        return rounds
    if saw_corrupt:
        log.warning(
            "no valid checkpoint survives in %s (all candidates torn or "
            "corrupt); starting fresh", ckpt_dir)
        _fault("checkpoint_unrecoverable")
    return 0
