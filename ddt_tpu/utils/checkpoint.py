"""Checkpoint/resume (SURVEY.md §5): per-tree restartable training.

A GBDT ensemble is tiny (KBs–MBs of node arrays), so checkpointing is simply:
after every K boosting rounds, atomically write the partial ensemble + a
cursor (completed rounds, config fingerprint). Resume = load node arrays into
the pre-allocated ensemble, rescore the partial ensemble to rebuild the
boosting state (Driver does that part), and continue the loop. Exactly
restartable because training is deterministic given the binned data
(SURVEY.md §5 "checkpoint/resume"); the fault-injection test kills a training
process mid-run and verifies the resumed ensemble matches an uninterrupted
one (tests/test_checkpoint.py).
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble

CKPT_FILE = "ensemble.npz"
CURSOR_FILE = "cursor.json"


def _cfg_fingerprint(cfg: TrainConfig) -> dict:
    """The config fields that must match for a checkpoint to be resumable."""
    d = dataclasses.asdict(cfg)
    # System knobs may legitimately differ across resume (e.g. resume on a
    # different partition count — distribution never changes results), and
    # n_trees may grow (resuming to train further is the point of resuming).
    for k in ("n_trees", "n_partitions", "feature_partitions",
              "host_partitions", "hist_impl", "backend",
              "matmul_input_dtype"):
        d.pop(k, None)
    # JSON round-trips tuples as lists; normalize so a saved fingerprint
    # compares equal to a freshly computed one.
    d["cat_features"] = list(d.get("cat_features", ()))
    return d


def save_checkpoint(
    ckpt_dir: str, ens: TreeEnsemble, cfg: TrainConfig, completed_rounds: int
) -> None:
    """Atomically persist the ensemble + cursor after `completed_rounds`."""
    os.makedirs(ckpt_dir, exist_ok=True)
    tmp = os.path.join(ckpt_dir, CKPT_FILE + ".tmp.npz")
    final = os.path.join(ckpt_dir, CKPT_FILE)
    np.savez_compressed(tmp, **ens.to_dict())
    os.replace(tmp, final)
    cur = {
        "completed_rounds": int(completed_rounds),
        "config": _cfg_fingerprint(cfg),
    }
    tmp_c = os.path.join(ckpt_dir, CURSOR_FILE + ".tmp")
    with open(tmp_c, "w") as f:
        json.dump(cur, f)
    os.replace(tmp_c, os.path.join(ckpt_dir, CURSOR_FILE))


def maybe_save(
    ckpt_dir: str | None,
    ens: TreeEnsemble,
    cfg: TrainConfig,
    completed_rounds: int,
    every: int | None = None,
) -> None:
    """save_checkpoint when a directory is configured and either `every`
    is None (forced — the end-of-training save) or `completed_rounds`
    hits the cadence. The single home of the save policy for the Driver
    and the streaming trainer."""
    if ckpt_dir is None:
        return
    if every is not None and completed_rounds % every != 0:
        return
    save_checkpoint(ckpt_dir, ens, cfg, completed_rounds)


def try_resume(ckpt_dir: str, ens: TreeEnsemble, cfg: TrainConfig) -> int:
    """Load a checkpoint into `ens` (in place). Returns completed rounds
    (0 = nothing to resume). Raises if the checkpoint's config is
    incompatible — resuming a different run would corrupt it silently."""
    cursor_path = os.path.join(ckpt_dir, CURSOR_FILE)
    ckpt_path = os.path.join(ckpt_dir, CKPT_FILE)
    if not (os.path.exists(cursor_path) and os.path.exists(ckpt_path)):
        return 0
    with open(cursor_path) as f:
        cur = json.load(f)
    # Fingerprint fields added over time default to their empty value so
    # checkpoints written before a field existed stay resumable.
    cur["config"].setdefault("cat_features", [])
    if cur["config"] != _cfg_fingerprint(cfg):
        raise ValueError(
            f"checkpoint at {ckpt_dir} was written by an incompatible config; "
            "refusing to resume. Delete the directory to start fresh."
        )
    saved = TreeEnsemble.load(ckpt_path)
    rounds = int(cur["completed_rounds"])
    if rounds > cfg.n_trees:
        raise ValueError(
            f"checkpoint at {ckpt_dir} has {rounds} completed rounds but "
            f"cfg.n_trees={cfg.n_trees}; raise n_trees to resume (a finished "
            "checkpoint cannot be shrunk in place)."
        )
    C = cfg.n_classes if cfg.loss == "softmax" else 1
    k = rounds * C
    ens.feature[:k] = saved.feature[:k]
    ens.threshold_bin[:k] = saved.threshold_bin[:k]
    ens.threshold_raw[:k] = saved.threshold_raw[:k]
    ens.is_leaf[:k] = saved.is_leaf[:k]
    ens.leaf_value[:k] = saved.leaf_value[:k]
    ens.split_gain[:k] = saved.split_gain[:k]
    ens.default_left[:k] = saved.default_left[:k]
    return rounds
