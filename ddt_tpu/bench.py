"""Benchmark harness (SURVEY.md §2 "Benchmark harness", §3 "Benchmark entry").

Measures the BASELINE.json metrics:
- `histogram`: HistogramBuilder throughput, M-rows/sec/chip — warm-up jit,
  then time K iterations of build_histograms alone (isolates metric #1 from
  the driver loop, matching the reference's "CPU-reference histogram
  throughput" comparison).
- `train`: end-to-end Higgs-style 100-tree build wallclock.
- `predict`: batch ensemble scoring rows/sec (the 10M-row × 1000-tree config).

All entry points return plain dicts; the CLI and the repo-root bench.py emit
them as JSON lines.
"""

from __future__ import annotations

import time

import numpy as np

from ddt_tpu.config import TrainConfig
from ddt_tpu.telemetry import counters as tele_counters


def _hist_inputs(rows, features, bins, n_nodes, seed):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    g = rng.standard_normal(rows).astype(np.float32)
    h = rng.random(rows).astype(np.float32) + 0.5
    node_index = rng.integers(0, n_nodes, size=rows).astype(np.int32)
    return Xb, g, h, node_index


def bench_histogram(
    backend: str = "tpu",
    rows: int = 1_000_000,
    features: int = 28,
    bins: int = 255,
    n_nodes: int = 32,
    iters: int = 10,
    partitions: int = 1,
    hist_impl: str = "auto",
    seed: int = 0,
    reps: int = 3,
) -> dict:
    """Time the HistogramBuilder kernel. n_nodes=32 ≈ the deepest (widest)
    level of the depth-6 Higgs config — the shape that dominates runtime.

    min-of-`reps` timing on BOTH backends: the TPU sits behind a remote
    tunnel with ±20% run-to-run wallclock noise and the CPU shares a noisy
    VM, so a single rep under- or over-states either side. The minimum is
    the closest observable to true kernel time, applied symmetrically."""
    from ddt_tpu.backends import get_backend

    cfg = TrainConfig(
        n_bins=bins, backend=backend, n_partitions=partitions,
        hist_impl=hist_impl,
    )
    be = get_backend(cfg)
    tele_counters.install_jax_listener()
    c0 = tele_counters.snapshot()
    Xb, g, h, node_index = _hist_inputs(rows, features, bins, n_nodes, seed)

    data = be.upload(Xb)
    dt = float("inf")
    if backend == "tpu":
        from ddt_tpu.utils.device import device_sync as sync

        g_d = be._put_rows(g)
        h_d = be._put_rows(h)
        ni_d = be._put_rows(node_index)
        out = be.build_histograms(data, g_d, h_d, ni_d, n_nodes)
        sync(out)                           # warm-up: compile + first run
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = be.build_histograms(data, g_d, h_d, ni_d, n_nodes)
            sync(out)
            dt = min(dt, (time.perf_counter() - t0) / iters)
    else:
        be.build_histograms(data, g, h, node_index, n_nodes)  # warm caches
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                be.build_histograms(data, g, h, node_index, n_nodes)
            dt = min(dt, (time.perf_counter() - t0) / iters)

    if backend == "tpu":
        from ddt_tpu.ops.histogram import resolve_hist_impl

        impl = resolve_hist_impl(
            hist_impl, n_nodes=n_nodes, n_features=features, n_bins=bins
        )
    else:
        impl = "native-c++" if getattr(be, "_native", None) else "numpy"

    n_chips = max(1, partitions)
    mrows = rows / dt / 1e6 / n_chips
    out = {
        "kernel": "histogram",
        "backend": backend,
        "impl": impl,
        "rows": rows, "features": features, "bins": bins, "n_nodes": n_nodes,
        "iters": iters, "partitions": partitions,
        "sec_per_build": dt,
        "mrows_per_sec_per_chip": mrows,
        # Telemetry counter: compiles triggered by this bench — a value
        # above the expected warm-up compile means the timed loop is
        # recompiling (shape churn), which invalidates the throughput.
        "jit_compiles": tele_counters.delta(c0)["jit_compiles"],
    }
    if backend == "tpu" and partitions == 1:
        # Roofline stamp (cost-observatory satellite): XLA's own cost
        # model for the measured program joined against the measured
        # per-build wallclock — achieved/peak fractions the benchwatch
        # sentinel can band (a silent dispatch regression shows up as a
        # utilization collapse even when absolute Mrows/s drift hides it).
        out.update(_roofline_util(
            "hist",
            lambda d, gg, hh, ni: be.build_histograms(d, gg, hh, ni,
                                                      n_nodes),
            (data, g_d, h_d, ni_d), dt))
    return out


def _roofline_util(prefix: str, fn, args: tuple,
                   sec_per_call: float) -> dict:
    """{<prefix>_roofline_flops_util, <prefix>_roofline_hbm_util} from
    costmodel.analyze of the measured program at the measured per-call
    wallclock (arrays ride as real arguments, never closure constants —
    XLA would fold constants out of the cost model). Returns {} when the
    analysis fails (capture must never fail a bench)."""
    from ddt_tpu.telemetry import costmodel

    rec = costmodel.analyze(fn, *args)
    if rec.get("error") or sec_per_call <= 0:
        return {}
    peaks = costmodel.peaks_for(rec.get("platform"))
    return {
        f"{prefix}_roofline_flops_util":
            round(rec["flops"] / sec_per_call / 1e9 / peaks["gflops"], 5),
        f"{prefix}_roofline_hbm_util":
            round(rec["bytes_accessed"] / sec_per_call / 1e9
                  / peaks["gbs"], 5),
    }


def _paired_ab_reps(bout, key_a, key_b, reps: int):
    """Order-alternating PAIRED reps — the ONE home of the two-arm A/B
    timing protocol that survives the tunnel's ±20% bands (round-4/5
    analysis: both arms of a pair share the band, so the per-rep ratio
    is robust where cross-run comparisons are not; alternating the
    order cancels residual within-pair drift). `bout(key)` runs and
    times one bout of that arm. Returns ({key: [dt, ...]},
    [dt_a / dt_b per rep]) — callers reduce per-arm dts (min or median)
    and take the median of the ratios as the A/B evidence."""
    dts = {key_a: [], key_b: []}
    ratios = []
    for rep in range(reps):
        order = (key_a, key_b) if rep % 2 == 0 else (key_b, key_a)
        pair = {}
        for k in order:
            pair[k] = bout(k)
            dts[k].append(pair[k])
        ratios.append(pair[key_a] / pair[key_b])
    return dts, ratios


def bench_histogram_ab(
    bins_a: int = 255,
    bins_b: int = 64,
    rows: int = 1_000_000,
    features: int = 28,
    n_nodes: int = 32,
    iters: int = 10,
    reps: int = 8,
    seed: int = 0,
) -> dict:
    """PAIRED two-arm histogram timing on the device backend.

    The remote-attached chip's wallclock drifts in ~±20% bands; round-4's
    sweep-11 epilogue (experiments/hist_ab_paired.py, docs/PERF.md)
    showed even interleaved min-of-reps can compare arms across bands
    and reverse a conclusion run to run. The robust statistic is the
    PER-REP PAIRED RATIO with the arm order alternating every rep: both
    arms of a pair share the band, so the median of ratios survives the
    tunnel. Per-arm throughputs are min-of-reps as before (the headline
    number); the ratio field is the A/B evidence."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.utils.device import device_sync as sync

    arms = {}
    for bins in (bins_a, bins_b):
        be = get_backend(TrainConfig(n_bins=bins, backend="tpu"))
        Xb, g, h, ni = _hist_inputs(rows, features, bins, n_nodes, seed)
        args = (be.upload(Xb), be._put_rows(g), be._put_rows(h),
                be._put_rows(ni))
        sync(be.build_histograms(*args, n_nodes))   # compile + first run
        arms[bins] = {"be": be, "args": args}

    def bout(bins):
        be, args = arms[bins]["be"], arms[bins]["args"]
        t0 = time.perf_counter()
        for _ in range(iters):
            out = be.build_histograms(*args, n_nodes)
        sync(out)
        return (time.perf_counter() - t0) / iters

    dts, ratios = _paired_ab_reps(bout, bins_a, bins_b, reps)
    dt_a, dt_b = min(dts[bins_a]), min(dts[bins_b])
    m_a, m_b = rows / dt_a / 1e6, rows / dt_b / 1e6
    out = {
        "kernel": "histogram_ab",
        "rows": rows, "features": features, "n_nodes": n_nodes,
        "bins_a": bins_a, "bins_b": bins_b,
        "mrows_a": m_a, "mrows_b": m_b,
        "ratio_b_over_a": float(np.median(ratios)),   # median paired ratio
    }
    # Roofline stamp for the headline (255-bin) arm: XLA's cost model at
    # the arm's measured per-build wallclock (cost-observatory satellite;
    # benchwatch bands the utilization fractions).
    be_a, args_a = arms[bins_a]["be"], arms[bins_a]["args"]
    out.update(_roofline_util(
        "hist",
        lambda d, gg, hh, ni: be_a.build_histograms(d, gg, hh, ni,
                                                    n_nodes),
        args_a, dt_a))
    return out


def bench_hist_fused_ab(
    rows: int = 1_000_000,
    features: int = 28,
    bins: int = 255,
    depth: int = 6,
    iters: int = 4,
    reps: int = 8,
    seed: int = 0,
) -> dict:
    """PAIRED fused-round A/B: the whole per-tree level loop
    (ops/grow.grow_tree — hist -> [subtract] -> gain -> route, one
    dispatch) with the sibling-subtraction trick ON vs OFF, at the
    Higgs-1M depth-6 shape. Same statistic as bench_histogram_ab (the
    only one that survives the tunnel's ±20% bands): per-rep PAIRED
    ratio with the arm order alternating every rep, median-of-ratios as
    the A/B evidence, min-of-reps per-arm timing as the headline.
    ratio_on_over_off > 1 means subtraction is winning; ~1.0 means the
    trick silently fell out of the dispatch (the floor's target).
    Throughputs are NOMINAL hist-row-equivalents (rows x depth levels /
    sec) so the two arms share a unit."""
    import functools

    import jax
    import jax.numpy as jnp

    from ddt_tpu.ops import grow as grow_ops
    from ddt_tpu.utils.device import device_sync as sync

    rng = np.random.default_rng(seed)
    Xb = jnp.asarray(rng.integers(0, bins, size=(rows, features),
                                  dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(rows).astype(np.float32))
    h = jnp.asarray((rng.random(rows) + 0.5).astype(np.float32))

    def build(sub):
        return jax.jit(functools.partial(
            grow_ops.grow_tree, max_depth=depth, n_bins=bins,
            reg_lambda=1.0, min_child_weight=1e-3, min_split_gain=0.0,
            hist_subtraction=sub))

    fns = {}
    for sub in (True, False):
        fns[sub] = build(sub)
        sync(fns[sub](Xb, g, h).leaf_value)   # compile + first run

    def bout(sub):
        t0 = time.perf_counter()
        for _ in range(iters):
            tree = fns[sub](Xb, g, h)
        sync(tree.leaf_value)
        return (time.perf_counter() - t0) / iters

    # ratio = dt_off / dt_on: > 1 means subtraction wins.
    dts, ratios = _paired_ab_reps(bout, False, True, reps)
    dt_on, dt_off = min(dts[True]), min(dts[False])
    out = {
        "kernel": "hist_fused_ab",
        "rows": rows, "features": features, "bins": bins, "depth": depth,
        "iters": iters, "reps": reps,
        "mrows_on": rows * depth / dt_on / 1e6,
        "mrows_off": rows * depth / dt_off / 1e6,
        "ratio_on_over_off": float(np.median(ratios)),
    }
    # Roofline stamp for the fused (subtraction-ON) round — XLA's own
    # cost model at the measured per-tree wallclock; benchwatch bands the
    # utilization fractions (a silent fallback to full-level builds shows
    # up here even when wallclock drift hides it).
    out.update(_roofline_util("hist_fused", fns[True], (Xb, g, h), dt_on))
    return out


def bench_hist_comms_ab(
    rows: int = 1_000_000,
    features: int = 28,
    bins: int = 255,
    depth: int = 6,
    iters: int = 4,
    reps: int = 8,
    seed: int = 0,
    host_partitions: int | None = None,
    n_partitions: int | None = None,
) -> dict:
    """PAIRED split-comms A/B on the pod mesh: the whole per-tree fused
    level loop with split_comms="allreduce" vs "reduce_scatter", same
    data, same mesh (docs/PERF.md "Histogram comms"). Default mesh is
    the pod shape — hosts x rows over every visible device (2 x N/2 when
    >= 4 devices, so the collective crosses the mesh's slow outer axis)
    — which is the CPU multi-device harness in tier-1 and the real
    ICI+DCN fabric on a chip image.

    Same statistic as bench_hist_fused_ab: per-rep PAIRED ratio with the
    arm order alternating every rep, median-of-ratios as the A/B
    evidence (ratio_allreduce_over_rs > 1 means reduce-scatter wins),
    min-of-reps per-arm timing as the headline. The deterministic
    per-level payload ratio (telemetry.counters.hist_allreduce_bytes,
    both modes) is stamped alongside — wallclock on a one-host virtual
    mesh moves little (localhost "wire"), the payload model is the
    invariant, and the chip floor (HIST_COMMS_AB_FLOOR) guards the
    wallclock side where a real fabric exists."""
    import jax

    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.telemetry import counters as tele_counters
    from ddt_tpu.utils.device import device_sync as sync

    n_dev = len(jax.devices())
    if host_partitions is None or n_partitions is None:
        if n_dev >= 4:
            host_partitions, n_partitions = 2, n_dev // 2
        else:
            host_partitions, n_partitions = 1, max(1, n_dev)
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    g = rng.standard_normal(rows).astype(np.float32)
    h = (rng.random(rows) + 0.5).astype(np.float32)

    arms = {}
    for mode in ("allreduce", "reduce_scatter"):
        cfg = TrainConfig(
            backend="tpu", n_bins=bins, max_depth=depth,
            host_partitions=host_partitions, n_partitions=n_partitions,
            split_comms=mode, seed=seed,
        )
        be = get_backend(cfg)
        data = be.upload(Xb)
        gd = be._put_rows(g)
        hd = be._put_rows(h)
        fn = be._grow_fn
        sync(fn(data, gd, hd)[0])       # compile + first run
        arms[mode] = (fn, data, gd, hd, be)

    def bout(mode):
        fn, data, gd, hd, _ = arms[mode]
        t0 = time.perf_counter()
        for _ in range(iters):
            packed, _delta = fn(data, gd, hd)
        sync(packed)
        return (time.perf_counter() - t0) / iters

    # ratio = dt_allreduce / dt_rs: > 1 means reduce-scatter wins
    # (_paired_ab_reps returns dt_key_a / dt_key_b per rep).
    dts, ratios = _paired_ab_reps(bout, "allreduce", "reduce_scatter",
                                  reps)
    dt_rs = min(dts["reduce_scatter"])
    dt_ar = min(dts["allreduce"])
    P = arms["allreduce"][4].row_shards
    bytes_ar = tele_counters.hist_allreduce_bytes(depth, features, bins,
                                                  partitions=P)
    bytes_rs = tele_counters.hist_allreduce_bytes(
        depth, features, bins, partitions=P, mode="reduce_scatter")
    return {
        "kernel": "hist_comms_ab",
        "rows": rows, "features": features, "bins": bins, "depth": depth,
        "iters": iters, "reps": reps,
        "host_partitions": host_partitions, "n_partitions": n_partitions,
        "mrows_rs": rows * depth / dt_rs / 1e6,
        "mrows_allreduce": rows * depth / dt_ar / 1e6,
        "ratio_allreduce_over_rs": float(np.median(ratios)),
        "payload_bytes_allreduce": bytes_ar,
        "payload_bytes_rs": bytes_rs,
        "payload_ratio": round(bytes_ar / bytes_rs, 3),
    }


def bench_hist_2d(
    rows: int = 200_000,
    features: int = 1024,
    bins: int = 64,
    depth: int = 6,
    iters: int = 4,
    reps: int = 8,
    seed: int = 0,
    n_partitions: int | None = None,
    feature_partitions: int | None = None,
) -> dict:
    """PAIRED 1D-row-mesh vs 2D (rows x features)-mesh whole-tree A/B at
    a WIDE shape (F >= 1k — the regime ROADMAP item 2 exists for: a
    replicated feature axis makes every device hold, build, and ship
    all F columns' histograms). Same device count both arms: the 1D arm
    puts every device on rows, the 2D arm splits them (Pr, Pf); both
    run the resolved split_comms (reduce_scatter on any row wire), so
    the A/B isolates the LAYOUT — per-device histogram slab F/(Pr·Pf)
    vs F/P, with the winner combine over both axes.

    Same statistic as bench_hist_comms_ab (the only one that survives
    the tunnel's ±20% bands): per-rep PAIRED ratio, order alternating
    every rep, median-of-ratios as the A/B evidence
    (ratio_1d_over_2d > 1 means the 2D mesh wins), min-of-reps per-arm
    timing as the headline. The deterministic per-tree payload ratio
    (telemetry.counters.hist_allreduce_bytes with the second axis) is
    stamped alongside — on a one-host virtual mesh wallclock moves
    little (localhost "wire"); the payload model is the invariant and
    the chip floor (HIST_2D_AB_FLOOR) guards the wallclock side where
    a real fabric exists."""
    import jax

    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.telemetry import counters as tele_counters
    from ddt_tpu.utils.device import device_sync as sync

    n_dev = len(jax.devices())
    if n_partitions is None or feature_partitions is None:
        if n_dev >= 4:
            n_partitions, feature_partitions = n_dev // 2, 2
        elif n_dev >= 2:
            n_partitions, feature_partitions = 1, 2
        else:
            raise ValueError("bench_hist_2d needs >= 2 devices")
    n_used = n_partitions * feature_partitions
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    g = rng.standard_normal(rows).astype(np.float32)
    h = (rng.random(rows) + 0.5).astype(np.float32)

    meshes = {"1d": (n_used, 1), "2d": (n_partitions, feature_partitions)}
    arms = {}
    for key, (pr, pf) in meshes.items():
        cfg = TrainConfig(
            backend="tpu", n_bins=bins, max_depth=depth,
            mesh_shape=(pr, pf), seed=seed,
        )
        be = get_backend(cfg)
        data = be.upload(Xb)
        gd = be._put_rows(g)
        hd = be._put_rows(h)
        fn = be._grow_fn
        sync(fn(data, gd, hd)[0])       # compile + first run
        arms[key] = (fn, data, gd, hd, be)

    def bout(key):
        fn, data, gd, hd, _ = arms[key]
        t0 = time.perf_counter()
        for _ in range(iters):
            packed, _delta = fn(data, gd, hd)
        sync(packed)
        return (time.perf_counter() - t0) / iters

    # ratio = dt_1d / dt_2d: > 1 means the 2D mesh wins.
    dts, ratios = _paired_ab_reps(bout, "1d", "2d", reps)
    dt_2d, dt_1d = min(dts["2d"]), min(dts["1d"])
    be_1d, be_2d = arms["1d"][4], arms["2d"][4]
    bytes_1d = tele_counters.hist_allreduce_bytes(
        depth, features, bins, partitions=be_1d.row_shards,
        mode=be_1d.split_comms)
    bytes_2d = tele_counters.hist_allreduce_bytes(
        depth, features, bins, partitions=be_2d.row_shards,
        feature_partitions=be_2d.feature_partitions,
        mode=be_2d.split_comms)
    # The acceptance comparator (ISSUE 11): the REPLICATED-FEATURE
    # allreduce baseline — every device receiving every column's bins —
    # on the same device count. payload_ratio = baseline / 2D effective
    # bytes, the deterministic 1/(Pr·Pf) factor the counter model
    # witnesses in-process (tests/test_mesh2d.py). NOTE the 1D-rs arm's
    # RECEIVED slab ties the 2D arm's at equal device count (both
    # F/n_dev per device); the 2D win over 1D-rs is the Pf-fold smaller
    # pre-collective histogram working set and ring traffic, which the
    # wallclock ratio — not the received-bytes model — measures.
    bytes_replicated = tele_counters.hist_allreduce_bytes(
        depth, features, bins, partitions=be_1d.row_shards,
        mode="allreduce")
    return {
        "kernel": "hist_2d_ab",
        "rows": rows, "features": features, "bins": bins, "depth": depth,
        "iters": iters, "reps": reps,
        "mesh_1d": list(meshes["1d"]), "mesh_2d": list(meshes["2d"]),
        "mrows_2d": rows * depth / dt_2d / 1e6,
        "mrows_1d": rows * depth / dt_1d / 1e6,
        "ratio_1d_over_2d": float(np.median(ratios)),
        "payload_bytes_replicated": bytes_replicated,
        "payload_bytes_1d": bytes_1d,
        "payload_bytes_2d": bytes_2d,
        "payload_ratio": round(bytes_replicated / bytes_2d, 3),
    }


def bench_hist_quant_ab(
    rows: int = 1_000_000,
    features: int = 28,
    bins: int = 255,
    depth: int = 6,
    iters: int = 4,
    reps: int = 8,
    seed: int = 0,
    grad_dtype: str = "int8",
) -> dict:
    """PAIRED quantized-gradient A/B: the whole per-tree fused level
    loop (ops/grow.grow_tree) with grad_dtype="f32" vs "int8" (or
    "int16"), same data, same shape — the ISSUE 14 tentpole's wallclock
    witness (docs/PERF.md "Quantized gradients"). Same statistic as
    bench_hist_fused_ab: per-rep PAIRED ratio with the arm order
    alternating every rep, median-of-ratios as the A/B evidence
    (ratio_f32_over_quant > 1 means the integer path wins), min-of-reps
    per-arm timing as the headline; throughputs are NOMINAL
    hist-row-equivalents (rows x depth / sec) so the arms share a unit.

    Both arms resolve their OWN sibling-subtraction default ('auto':
    integer hists subtract exactly everywhere, f32 only on a real chip)
    — the A/B measures the shipped configs, not a lab pairing. The
    deterministic payload_ratio stamps the g/h HBM-stream byte model
    (telemetry.counters.grad_stream_bytes — 4x int8, 2x int16): on CPU
    the wallclock moves little (the interpreted kernel dominates), the
    byte model is the invariant, and the chip floor
    (HIST_QUANT_AB_FLOOR) guards the wallclock side where HBM bandwidth
    is real."""
    import functools

    import jax
    import jax.numpy as jnp

    from ddt_tpu.ops import grow as grow_ops
    from ddt_tpu.utils.device import device_sync as sync

    rng = np.random.default_rng(seed)
    Xb = jnp.asarray(rng.integers(0, bins, size=(rows, features),
                                  dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(rows).astype(np.float32))
    h = jnp.asarray((rng.random(rows) * 0.25).astype(np.float32))

    def build(dt):
        from ddt_tpu.ops.grow import resolve_hist_subtraction

        return jax.jit(functools.partial(
            grow_ops.grow_tree, max_depth=depth, n_bins=bins,
            reg_lambda=1.0, min_child_weight=1e-3, min_split_gain=0.0,
            hist_subtraction=resolve_hist_subtraction(
                "auto", integer_hists=dt != "f32"),
            grad_dtype=dt, quant_seed=seed))

    fns = {}
    for dt in ("f32", grad_dtype):
        fns[dt] = build(dt)
        sync(fns[dt](Xb, g, h).leaf_value)   # compile + first run

    def bout(dt):
        t0 = time.perf_counter()
        for _ in range(iters):
            tree = fns[dt](Xb, g, h)
        sync(tree.leaf_value)
        return (time.perf_counter() - t0) / iters

    # ratio = dt_f32 / dt_quant: > 1 means the integer path wins.
    dts, ratios = _paired_ab_reps(bout, "f32", grad_dtype, reps)
    dt_q = min(dts[grad_dtype])
    dt_f = min(dts["f32"])
    bytes_f = tele_counters.grad_stream_bytes(rows, depth, "f32")
    bytes_q = tele_counters.grad_stream_bytes(rows, depth, grad_dtype)
    out = {
        "kernel": "hist_quant_ab",
        "rows": rows, "features": features, "bins": bins, "depth": depth,
        "iters": iters, "reps": reps, "grad_dtype": grad_dtype,
        "mrows_quant": rows * depth / dt_q / 1e6,
        "mrows_f32": rows * depth / dt_f / 1e6,
        "ratio_f32_over_quant": float(np.median(ratios)),
        "grad_stream_bytes_f32": bytes_f,
        "grad_stream_bytes_quant": bytes_q,
        "payload_ratio": round(bytes_f / bytes_q, 3),
    }
    # Roofline stamp for the quantized arm: XLA's cost model at the
    # measured per-tree wallclock (benchwatch bands the fractions; an
    # integer path silently falling back to f32 streams shows up as an
    # HBM-utilization jump even when wallclock drift hides it).
    out.update(_roofline_util("hist_quant", fns[grad_dtype], (Xb, g, h),
                              dt_q))
    return out


def bench_histogram_one_dispatch(
    rows: int = 1_000_000,
    features: int = 28,
    bins: int = 255,
    n_nodes: int = 32,
    iters: int = 10,
    reps: int = 8,
    seed: int = 0,
) -> dict:
    """One-dispatch headline twin: `iters` kernel invocations inside ONE
    jitted lax.fori_loop — two tunnel round-trips per rep instead of one
    per dispatch. experiments/hist_dispatch_ab.py measured the
    dispatch-loop protocol at 33% within-window spread (incl. spuriously
    FAST samples that min-of-reps then reports) vs 7.6% for this
    formulation in the same window; device-rate bands remain real across
    windows (docs/PERF.md round-5 addendum), but this statistic is far
    better conditioned within one. A tiny data dependence (g advanced by
    a scalar read of the previous histogram) keeps XLA from hoisting the
    loop body; the +iters elementwise adds on g are noise against the
    histogram passes.

    Reports BOTH median-of-reps and min-of-reps (round-5 advisor
    finding): min-of-reps is the very statistic the dispatch-loop
    docstring criticizes for promoting transient fast-tail excursions to
    the run's value, and with the external 45-65 drift min-of-8 still
    biases the floored metric toward lucky windows. The median (the
    stat experiments/hist_dispatch_ab.py already uses) is the headline
    `mrows_per_sec_per_chip`; the min is kept as `_min` fields for
    comparability with earlier artifacts."""
    import jax
    import jax.numpy as jnp

    from ddt_tpu.ops import histogram as hist_ops

    Xb_h, g_h, h_h, ni_h = _hist_inputs(rows, features, bins, n_nodes, seed)
    Xb = jnp.asarray(Xb_h)
    g0 = jnp.asarray(g_h)
    h = jnp.asarray(h_h)
    ni = jnp.asarray(ni_h)

    @jax.jit
    def k_in_one(g):
        def body(_, carry):
            g2, acc = carry
            out = hist_ops.build_histograms(Xb, g2, h, ni, n_nodes, bins)
            s = out[0, 0, 0, 0] * jnp.float32(1e-30)
            return g2 + s, acc + s
        return jax.lax.fori_loop(0, iters, body, (g, jnp.float32(0.0)))[1]

    float(k_in_one(g0))                      # compile + first run
    dts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(k_in_one(g0))                  # scalar fetch = the barrier
        dts.append((time.perf_counter() - t0) / iters)
    dt_med = float(np.median(dts))
    dt_min = float(np.min(dts))
    return {
        "kernel": "histogram_one_dispatch",
        "rows": rows, "features": features, "bins": bins,
        "n_nodes": n_nodes, "iters": iters,
        "sec_per_build": dt_med,
        "sec_per_build_min": dt_min,
        "mrows_per_sec_per_chip": rows / dt_med / 1e6,
        "mrows_per_sec_per_chip_min": rows / dt_min / 1e6,
    }


def bench_train(
    backend: str = "tpu",
    rows: int = 1_000_000,
    features: int = 28,
    bins: int = 255,
    trees: int = 100,
    depth: int = 6,
    partitions: int = 1,
    hist_impl: str = "auto",
    seed: int = 0,
    run_log=None,
) -> dict:
    """End-to-end boosted-build wallclock (the Higgs-1M/depth-6/100-tree
    config when called with defaults). `run_log` (path or telemetry
    RunLog) attaches the structured run log to the TIMED run — the bench
    artifact then carries per-round records and counters alongside the
    headline wallclock."""
    from ddt_tpu import api
    from ddt_tpu.data import datasets
    from ddt_tpu.data.quantizer import quantize

    X, y = datasets.synthetic_binary(rows, n_features=features, seed=seed)
    Xb, _ = quantize(X, n_bins=bins, seed=seed)
    cfg = TrainConfig(
        n_trees=trees, max_depth=depth, n_bins=bins, backend=backend,
        n_partitions=partitions, hist_impl=hist_impl, seed=seed,
    )
    tele_counters.install_jax_listener()
    # Warm-up: compile the per-tree program on a 2-tree run, then time.
    api.train(Xb, y, cfg.replace(n_trees=2), binned=True, log_every=10**9)
    c0 = tele_counters.snapshot()
    t0 = time.perf_counter()
    res = api.train(Xb, y, cfg, binned=True, log_every=10**9,
                    run_log=run_log)
    dt = time.perf_counter() - t0
    return {
        "kernel": "train",
        "backend": backend, "rows": rows, "trees": trees, "depth": depth,
        "partitions": partitions,
        "wallclock_s": dt,
        "trees_per_sec": trees / dt,
        "final_train_loss": res.history[-1]["train_loss"]
        if res.history else None,
        # Compiles INSIDE the timed run (telemetry.counters). Nonzero is
        # expected once per distinct block/round shape (the warm-up's
        # 2-round block differs from the timed blocks); a value growing
        # WITH `trees` means per-round shape churn — the silent killer
        # the counter exists to surface (arXiv:1810.09868).
        "jit_compiles_timed": tele_counters.delta(c0)["jit_compiles"],
    }


def _predict_setup(rows, features, bins, trees, depth, seed, backend="tpu",
                   partitions=1):
    """(backend, Xb, ensemble) for the scoring benches — random full
    trees (all internal nodes split; plausible worst case), shared by
    bench_predict and bench_predict_both so the two can't drift."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.models.tree import empty_ensemble

    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    n_nodes = 2 ** (depth + 1) - 1
    ens = empty_ensemble(trees, depth, features, 0.1, 0.0, "logloss")
    ens.feature[:] = rng.integers(0, features, size=(trees, n_nodes))
    ens.threshold_bin[:] = rng.integers(0, bins - 1, size=(trees, n_nodes))
    ens.is_leaf[:, (n_nodes // 2):] = True
    ens.leaf_value[:] = rng.standard_normal(
        (trees, n_nodes)).astype(np.float32)
    cfg = TrainConfig(backend=backend, n_partitions=partitions, n_bins=bins)
    return get_backend(cfg), Xb, ens


def bench_predict(
    backend: str = "tpu",
    rows: int = 1_000_000,
    features: int = 28,
    bins: int = 255,
    trees: int = 1000,
    depth: int = 6,
    partitions: int = 1,
    seed: int = 0,
) -> dict:
    """Batch inference throughput (the 1000-tree × large-batch config)."""
    be, Xb, ens = _predict_setup(rows, features, bins, trees, depth, seed,
                                 backend, partitions)
    # Warm-up with one FULL untimed pass: jit caches are shape-keyed and
    # device backends chunk rows internally, so only an identical call is
    # guaranteed to compile every shape (incl. a remainder chunk) the timed
    # run will hit.
    be.predict_raw(ens, Xb)
    t0 = time.perf_counter()
    out = be.predict_raw(ens, Xb)
    dt = time.perf_counter() - t0
    assert out.shape[0] == rows
    return {
        "kernel": "predict",
        "backend": backend, "rows": rows, "trees": trees, "depth": depth,
        "wallclock_s": dt,
        "mrows_per_sec": rows / dt / 1e6,
    }


def bench_predict_both(
    rows: int = 10_000_000,
    features: int = 28,
    bins: int = 255,
    trees: int = 1000,
    depth: int = 6,
    seed: int = 0,
    reps: int = 2,
) -> tuple[dict, dict, dict]:
    """(resident, total, compute) predict measurements sharing ONE
    dataset, ensemble, and warm-up pass — the 280 MB batch and 1000-tree
    model are built once, the warm full pass compiles every chunk shape
    the timed paths hit, and only the timing loops differ. The resident
    arm (batch device-uploaded ONCE, outside timing) measures scoring
    compute + the overlapped result fetch rather than the host→device
    link — through the remote tunnel the 280 MB upload varies 16-50 s
    run to run and would swamp any kernel regression the floor exists to
    catch. The COMPUTE arm goes one step further (round-5 phase
    breakdown: the D2H fetch is ~65% of even the resident wallclock and
    carries the tunnel's bands): it syncs the chunk outputs on device
    without copying them back, isolating the descent/leaf-select kernels
    the 0.8-era floor was actually trying to guard — a band-stable
    number a tight floor can sit under. The repo-root bench floors
    resident AND compute and records total as context."""
    import jax

    from ddt_tpu.utils.device import device_sync

    be, Xb, ens = _predict_setup(rows, features, bins, trees, depth, seed)
    be.predict_raw(ens, Xb)                       # warm-up, all shapes
    data = jax.device_put(Xb)
    device_sync(data)
    # Which traversal the auto dispatch resolved to (pallas on a real TPU
    # at VMEM-fitting shapes since the inference overhaul; one-hot
    # otherwise) — recorded so floor trips can be attributed.
    from ddt_tpu.ops.predict import resolve_use_pallas

    tpad = -(-trees // 64) * 64
    impl = ("pallas" if resolve_use_pallas(None, True, tpad, 64, depth,
                                           features, 1) else "onehot")
    base = {"kernel": "predict", "backend": "tpu", "rows": rows,
            "trees": trees, "depth": depth, "impl": impl}
    out = []
    for resident, arg, n in ((True, data, reps), (False, Xb, 1)):
        dt = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            got = be.predict_raw(ens, arg)
            dt = min(dt, time.perf_counter() - t0)
        assert got.shape[0] == rows
        out.append({**base, "resident": resident, "wallclock_s": dt,
                    "mrows_per_sec": rows / dt / 1e6})

    # Compute-only arm: same chunked programs, outputs synced on device,
    # nothing row-sized crosses to host.
    fn, ens_dev = be._predict_fn(ens)
    chunk = be.PREDICT_ROW_CHUNK
    dt = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [fn(*ens_dev, data[i:i + chunk])
                for i in range(0, rows, chunk)]
        for o in outs:
            device_sync(o)
        dt = min(dt, time.perf_counter() - t0)
    rec = {**base, "resident": "compute_only", "wallclock_s": dt,
           "mrows_per_sec": rows / dt / 1e6}
    # Roofline stamp for the scoring kernel (cost-observatory satellite):
    # one full-size chunk's program at its share of the measured compute
    # wallclock (the chunks are homogeneous up to the remainder).
    n_chunks = -(-rows // chunk)
    rec.update(_roofline_util("predict", fn,
                              (*ens_dev, data[:min(chunk, rows)]),
                              dt / n_chunks))
    out.append(rec)
    return out[0], out[1], out[2]


def bench_predict_pallas_ab(
    rows: int = 4_000_000,
    features: int = 28,
    bins: int = 255,
    trees: int = 1000,
    depth: int = 6,
    seed: int = 0,
    reps: int = 8,
) -> dict:
    """PAIRED pallas-vs-one-hot traversal timing, compute-only + resident.

    Same protocol as bench_histogram_ab (the only statistic that survives
    the tunnel's ±20% bands): per-rep PAIRED ratio with the arm order
    alternating every rep, median-of-ratios as the A/B evidence and
    median-of-reps per-arm throughput as the headline (the histogram
    protocol's statistic — min-of-reps promotes fast-tail excursions).
    Both arms run predict_raw_effective on the SAME device-resident
    CompiledEnsemble arrays and batch, so only the traversal formulation
    differs; outputs are asserted equal first (the kernel's exactness
    contract, witnessed per bench run like split_agreement).

    Meaningful on a real chip only — off-TPU the pallas arm runs the
    interpreter (minutes per dispatch); the repo-root bench gates on
    on_tpu."""
    import jax
    import jax.numpy as jnp

    from ddt_tpu.ops import predict as predict_ops
    from ddt_tpu.utils.device import device_sync

    _, Xb, ens = _predict_setup(rows, features, bins, trees, depth, seed)
    ce = ens.compile(tree_chunk=64)
    dev = [jnp.asarray(a) for a in ce.arrays()]
    Xd = jax.device_put(Xb)
    device_sync(Xd)

    def run(use_pallas):
        out = predict_ops.predict_raw_effective(
            *dev, Xd, max_depth=ce.max_depth,
            learning_rate=ce.learning_rate, base=ce.base_score,
            n_classes=ce.n_classes_out, tree_chunk=ce.tree_chunk,
            use_pallas=use_pallas)
        device_sync(out)
        return out
    # Warm-up compiles both arms AND witnesses the exactness contract.
    a0, b0 = run(True), run(False)
    assert bool(jnp.all(a0 == b0)), \
        "pallas traversal diverged from the one-hot path"

    def bout(use_pallas):
        t0 = time.perf_counter()
        run(use_pallas)
        return time.perf_counter() - t0

    # ratio = dt_onehot / dt_pallas: > 1 means pallas faster.
    dts, ratios = _paired_ab_reps(bout, False, True, reps)
    med = {arm: float(np.median(v)) for arm, v in dts.items()}
    return {
        "kernel": "predict_pallas_ab",
        "rows": rows, "features": features, "bins": bins,
        "trees": trees, "depth": depth, "reps": reps,
        "pallas_mrows_per_sec": rows / med[True] / 1e6,
        "onehot_mrows_per_sec": rows / med[False] / 1e6,
        "ratio_pallas_over_onehot": float(np.median(ratios)),
        "exact_match": True,            # asserted above
    }


def bench_serve_latency(
    backend: str = "tpu",
    rows: int = 20_000,
    features: int = 16,
    bins: int = 63,
    trees: int = 50,
    depth: int = 4,
    qps_points: tuple = (50, 200, 8000),
    n_requests: int = 200,
    max_wait_ms: float = 1.0,
    max_batch: int = 64,
    quantize: bool = False,
    seed: int = 0,
) -> dict:
    """Latency-under-load for the serving tier (ISSUE 8 acceptance arm;
    CPU-runnable — the admission/queueing behavior under test is host
    code, the model is small enough that per-dispatch device time is
    milliseconds on any platform).

    Protocol:
    - COLD comparator: one `api.predict` single-row call against a
      FRESH backend instance with nothing cached (first-call compile +
      CompiledEnsemble build + upload) — what an RPC handler that calls
      the batch API per request would pay on a cold model, the exact
      path `cli serve` exists to replace.
    - then, per open-loop arrival rate in `qps_points`: `n_requests`
      single-row requests submitted on schedule (arrival i at t0 +
      i/qps, independent of completions — open loop, so queueing shows
      up as latency rather than rate throttling), p50/p99 latency and
      coalesce width recorded from the engine's own stats. The TOP
      point must SATURATE the admission window on any box — at 8000/s
      the default 1 ms window alone gathers ~8 arrivals irrespective of
      per-dispatch speed, which is what keeps the repo-root bench's
      SERVE_COALESCE_MIN floor a property of the batcher, not of the
      host's dispatch latency.

    Stamped into BENCH artifacts as serve_* metrics and banded by
    tools/benchwatch (latency lower-is-better — the direction table
    grew the latency sign for exactly these)."""
    import threading

    from ddt_tpu import api
    from ddt_tpu.serve.engine import ServeEngine

    rng = np.random.default_rng(seed)
    be0, Xb, ens = _predict_setup(rows, features, bins, trees, depth, seed,
                                  backend=backend)
    del be0     # the serving engine builds its own backend below
    bundle = api.ModelBundle(ensemble=ens, mapper=None)

    # Cold comparator: a backend built OUTSIDE the module cache
    # (use_cache=False — the cache key normalizes cfg.seed away at
    # subsample=1.0, so a merely-distinct config would alias the warm
    # instance) so its device-resident predict cache is empty, AND the
    # process-global jit trace/executable caches cleared so the call
    # pays compile + build + upload every run — without this, an
    # in-process repeat (a second bench arm, a quantize=True A/B leg)
    # gets the first run's executable back in ~1 ms and the 10x
    # cold-over-p99 floor false-fails as a PERF REGRESSION. The engine
    # below re-traces its bucket shapes at warm-up, off the request
    # path — bench time, not serving latency.
    import jax as _jax

    from ddt_tpu.backends import get_backend as _get_backend

    _jax.clear_caches()
    cold_cfg = TrainConfig(backend=backend, n_bins=bins)
    t0 = time.perf_counter()
    api.predict(ens, Xb[:1], binned=True,
                backend=_get_backend(cold_cfg, use_cache=False))
    cold_ms = (time.perf_counter() - t0) * 1e3

    cfg = TrainConfig(backend=backend, n_bins=bins,
                      predict_impl="lut" if quantize else "auto")
    engine = ServeEngine(bundle, cfg, max_wait_ms=max_wait_ms,
                         max_batch=max_batch, quantize=quantize)
    arms = []
    for qps in qps_points:
        engine.stats.window_summary(reset=True)      # fresh window
        pendings = []
        t_start = time.perf_counter()
        for i in range(n_requests):
            target = t_start + i / qps
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)             # open-loop arrival
            r = int(rng.integers(0, rows))
            pendings.append(engine.predict_async(Xb[r:r + 1]))
        for p in pendings:
            p.result(timeout=60.0)
        w = engine.stats.window_summary(reset=True)
        arms.append({"qps": qps, **{k: w[k] for k in
                                    ("requests", "batches", "p50_ms",
                                     "p99_ms", "p999_ms", "coalesce_mean",
                                     "coalesce_max", "queue_depth_max")}})
    engine.close()
    # Headline = the MIDDLE qps point (index len//2): busy enough to
    # coalesce, not so hot that the arm measures pure saturation.
    head = arms[len(arms) // 2]
    return {
        "kernel": "serve_latency",
        "backend": backend, "rows_model": rows, "trees": trees,
        "depth": depth, "features": features,
        "max_wait_ms": max_wait_ms, "max_batch": max_batch,
        "quantized": bool(quantize),
        "cold_predict_ms": round(cold_ms, 3),
        "arms": arms,
        "serve_qps": head["qps"],
        "serve_p50_ms": head["p50_ms"],
        "serve_p99_ms": head["p99_ms"],
        "serve_p999_ms": head["p999_ms"],
        "serve_coalesce_mean": head["coalesce_mean"],
        "serve_coalesce_max": max(a["coalesce_max"] for a in arms),
        "serve_cold_over_p99": (round(cold_ms / head["p99_ms"], 2)
                                if head["p99_ms"] > 0 else None),
    }


def bench_predict_lut_ab(
    rows: int = 4_000_000,
    features: int = 28,
    bins: int = 255,
    trees: int = 1000,
    depth: int = 6,
    seed: int = 0,
    reps: int = 8,
) -> dict:
    """PAIRED quantized-LUT vs f32 traversal timing — the serving tier's
    A/B arm (ISSUE 8). Same statistic as bench_predict_pallas_ab (the
    only one that survives the tunnel's ±20% bands): per-rep PAIRED
    ratio, order alternating every rep, median-of-ratios as the
    evidence. The f32 arm is whatever the auto dispatch resolves
    (Pallas on a real chip); the LUT arm streams raw uint8 rows against
    int8/fp16 tables. The error contract is witnessed per run: max
    |lut - f32| must sit under the tables' computed bound.

    Meaningful on a real chip only — off-TPU both Pallas arms run the
    interpreter; the repo-root bench gates on on_tpu."""
    import jax
    import jax.numpy as jnp

    from ddt_tpu.ops import predict as predict_ops
    from ddt_tpu.ops import predict_lut
    from ddt_tpu.utils.device import device_sync

    _, Xb, ens = _predict_setup(rows, features, bins, trees, depth, seed)
    ce = ens.compile(tree_chunk=64)
    tables = ce.quantize()
    dev_f32 = [jnp.asarray(a) for a in ce.arrays()]
    lut_ops = tuple(jnp.asarray(a)
                    for a in predict_lut.lut_device_operands(tables))
    Xd = jax.device_put(Xb)
    device_sync(Xd)
    lut_static = dict(
        max_depth=tables.max_depth, learning_rate=tables.learning_rate,
        base=tables.base_score, n_classes=tables.n_classes_out,
        tree_chunk=tables.tree_chunk,
        n_trees_padded=tables.n_trees_padded,
        missing_bin_value=tables.missing_bin_value,
        use_missing=tables.eff_dl is not None,
        use_cat=tables.eff_cat is not None,
        use_scale=tables.leaf_scale is not None)
    lut_jit = jax.jit(lambda *a: predict_lut.predict_effective_lut_ops(
        a[:-1], a[-1], **lut_static))

    def run(arm):
        if arm == "lut":
            out = lut_jit(*lut_ops, Xd)
        else:
            out = predict_ops.predict_raw_effective(
                *dev_f32, Xd, max_depth=ce.max_depth,
                learning_rate=ce.learning_rate, base=ce.base_score,
                n_classes=ce.n_classes_out, tree_chunk=ce.tree_chunk)
        device_sync(out)
        return out

    # Warm-up compiles both arms AND witnesses the error contract.
    a0, b0 = np.asarray(run("lut")), np.asarray(run("f32"))
    err = float(np.abs(a0 - b0).max())
    assert err <= tables.max_abs_err * (1 + 1e-5) + 1e-6, \
        (err, tables.max_abs_err)

    def bout(arm):
        t0 = time.perf_counter()
        run(arm)
        return time.perf_counter() - t0

    # ratio = dt_f32 / dt_lut: > 1 means the quantized path wins.
    dts, ratios = _paired_ab_reps(bout, "f32", "lut", reps)
    med = {arm: float(np.median(v)) for arm, v in dts.items()}
    return {
        "kernel": "predict_lut_ab",
        "rows": rows, "features": features, "bins": bins,
        "trees": trees, "depth": depth, "reps": reps,
        "lut_mrows_per_sec": rows / med["lut"] / 1e6,
        "f32_mrows_per_sec": rows / med["f32"] / 1e6,
        "ratio_lut_over_f32": float(np.median(ratios)),
        "lut_max_abs_err": err,
        "lut_err_bound": tables.max_abs_err,
    }


def bench_predict_lut4_ab(
    rows: int = 4_000_000,
    features: int = 28,
    bins: int = 15,
    trees: int = 1000,
    depth: int = 6,
    seed: int = 0,
    reps: int = 8,
    ab: "bool | None" = None,
    express_trees: int = 50,
    express_depth: int = 4,
    express_features: int = 16,
    express_bins: int = 15,
    n_single: int = 120,
    n_storm: int = 300,
    max_wait_ms: float = 20.0,
) -> dict:
    """int4 tier + express lane, the two ISSUE 12 measurements in one
    artifact.

    PART 1 — paired int8-vs-int4 A/B (the bench_predict_lut_ab
    protocol: alternating order, median-of-ratios): both quantized
    kernels at the bench shape, `bins=15` so the int4 thresholds ride
    the nibble pack (the TreeLUT regime the tier exists for). The int4
    error contract is witnessed per run against the f32 one-hot path.
    Meaningful on a real chip only (off-TPU both arms run the Pallas
    interpreter) — `ab=None` auto-skips there; the repo-root bench
    gates on on_tpu and the chip floor is PREDICT_LUT4_AB_FLOOR.

    PART 2 — express-lane two-regime arm (host behavior, runs on every
    platform): a small int4-served engine measured in BOTH regimes.
    EMPTY QUEUE: sequential single-row requests — with the lane on,
    latency is dispatch only; with it off, every lone request eats the
    admission window, so `max_wait_ms` (deliberately large, 20 ms, to
    dominate host noise) is the coalesced path's latency FLOOR and
    express p99 must sit measurably below it. SATURATED: a burst of
    async submissions keeps the queue non-empty, the lane closes, and
    both engines coalesce — express-on p99 must not regress the
    express-off p99 (the lane's never-worse contract)."""
    import jax
    import jax.numpy as jnp

    from ddt_tpu import api
    from ddt_tpu.ops import predict as predict_ops
    from ddt_tpu.ops import predict_lut
    from ddt_tpu.serve.engine import ServeEngine
    from ddt_tpu.utils.device import device_sync

    out = {
        "kernel": "predict_lut4_ab",
        "rows": rows, "features": features, "bins": bins,
        "trees": trees, "depth": depth, "reps": reps,
        "express_max_wait_ms": max_wait_ms,
    }
    if ab is None:
        ab = jax.default_backend() == "tpu"

    if ab:
        _, Xb, ens = _predict_setup(rows, features, bins, trees, depth,
                                    seed)
        ce = ens.compile(tree_chunk=64)
        t8 = ce.quantize()
        t4 = ce.quantize(leaf_dtype="int4")
        pk = t4.pack_int4()
        ops8 = tuple(jnp.asarray(a)
                     for a in predict_lut.lut_device_operands(t8))
        ops4 = tuple(jnp.asarray(a) for a in pk.ops)
        Xd = jax.device_put(Xb)
        device_sync(Xd)
        st8 = dict(
            max_depth=t8.max_depth, learning_rate=t8.learning_rate,
            base=t8.base_score, n_classes=t8.n_classes_out,
            tree_chunk=t8.tree_chunk, n_trees_padded=t8.n_trees_padded,
            missing_bin_value=t8.missing_bin_value,
            use_missing=t8.eff_dl is not None,
            use_cat=t8.eff_cat is not None,
            use_scale=t8.leaf_scale is not None)
        jit8 = jax.jit(lambda *a: predict_lut.predict_effective_lut_ops(
            a[:-1], a[-1], **st8))
        st4 = pk.static_kwargs()
        jit4 = jax.jit(lambda *a: predict_lut.predict_effective_lut4_ops(
            a[:-1], a[-1], **st4))

        def run(arm):
            o = (jit4(*ops4, Xd) if arm == "int4" else jit8(*ops8, Xd))
            device_sync(o)
            return o

        # Warm-up compiles both arms AND witnesses the int4 error
        # contract against the true f32 one-hot answer.
        a4 = np.asarray(run("int4"))
        np.asarray(run("int8"))
        f32 = np.asarray(predict_ops.predict_raw_effective(
            *[jnp.asarray(a) for a in ce.arrays()], Xd,
            max_depth=ce.max_depth, learning_rate=ce.learning_rate,
            base=ce.base_score, n_classes=ce.n_classes_out,
            tree_chunk=ce.tree_chunk, use_pallas=False))
        err = float(np.abs(a4 - f32).max())
        assert err <= t4.max_abs_err * (1 + 1e-5) + 1e-6, \
            (err, t4.max_abs_err)

        def bout(arm):
            t0 = time.perf_counter()
            run(arm)
            return time.perf_counter() - t0

        # ratio = dt_int8 / dt_int4: > 1 means the bit-packed tier wins.
        dts, ratios = _paired_ab_reps(bout, "int8", "int4", reps)
        med = {arm: float(np.median(v)) for arm, v in dts.items()}
        out.update({
            "lut4_mrows_per_sec": rows / med["int4"] / 1e6,
            "lut8_mrows_per_sec": rows / med["int8"] / 1e6,
            "ratio_int4_over_int8": float(np.median(ratios)),
            "lut4_max_abs_err": err,
            "lut4_err_bound": t4.max_abs_err,
            "lut4_thr_packed": pk.thr_packed,
        })

    # ---- express-lane two-regime arm (host code, every platform) ----
    _, Xe, ens_e = _predict_setup(4096, express_features, express_bins,
                                  express_trees, express_depth, seed)
    bundle = api.ModelBundle(ensemble=ens_e, mapper=None)
    cfg = TrainConfig(backend="tpu", n_bins=express_bins,
                      predict_impl="lut4")
    rng = np.random.default_rng(seed)

    def one_engine(express: bool) -> dict:
        eng = ServeEngine(bundle, cfg, max_wait_ms=max_wait_ms,
                          max_batch=64, quantize="int4",
                          express_lane=express)
        try:
            # EMPTY-QUEUE regime: strictly sequential singles — the
            # queue is empty at every submit by construction.
            eng.stats.window_summary(reset=True)
            for _ in range(n_single):
                r = int(rng.integers(0, len(Xe)))
                eng.predict(Xe[r:r + 1], timeout=60.0)
            empty = eng.stats.window_summary(reset=True)
            # SATURATED regime: concurrent submitters keep the queue
            # non-empty (a single-threaded async burst would SERIALIZE
            # through the express lane — each synchronous express
            # dispatch completes before the next submit, so the queue
            # never forms); under real concurrency the lane closes and
            # coalescing takes over.
            import threading

            n_threads = 16
            per = max(1, n_storm // n_threads)
            barrier = threading.Barrier(n_threads)
            errs: list = []

            def worker(tid):
                rngl = np.random.default_rng(seed + 1 + tid)
                barrier.wait()
                for _ in range(per):
                    r = int(rngl.integers(0, len(Xe)))
                    try:
                        eng.predict(Xe[r:r + 1], timeout=120.0)
                    # Collected and asserted empty after the join — a
                    # failed storm request is the bench's own verdict.
                    except Exception as e:  # ddtlint: disable=broad-except
                        errs.append(repr(e))

            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
            if errs:
                raise AssertionError(
                    f"saturated-arm requests failed: {errs[:3]}")
            sat = eng.stats.window_summary(reset=True)
            return {"empty": empty, "sat": sat}
        finally:
            eng.close()

    on = one_engine(express=True)
    off = one_engine(express=False)
    out.update({
        "express_empty_p50_ms": on["empty"]["p50_ms"],
        "express_empty_p99_ms": on["empty"]["p99_ms"],
        "coalesced_empty_p50_ms": off["empty"]["p50_ms"],
        "coalesced_empty_p99_ms": off["empty"]["p99_ms"],
        "express_hits_empty": on["empty"]["express"],
        "express_saturated_p99_ms": on["sat"]["p99_ms"],
        "coalesced_saturated_p99_ms": off["sat"]["p99_ms"],
        "express_hits_saturated": on["sat"]["express"],
        "express_gain": (round(off["empty"]["p99_ms"]
                               / on["empty"]["p99_ms"], 2)
                         if on["empty"]["p99_ms"] > 0 else None),
    })
    return out


def bench_registry_cold_load(
    backend: str = "tpu",
    features: int = 16,
    bins: int = 63,
    trees: int = 100,
    depth: int = 5,
    max_batch: int = 64,
    quantize: bool = False,
    seed: int = 0,
) -> dict:
    """Cold-start-to-serving latency: restore-from-registry (AOT
    deserialize + per-bucket XLA compile + warm) vs the full in-process
    ServableModel build (validate + compile layout + TRACE every bucket
    + compile + warm) — the prologue the registry's export boundary
    exists to amortize (ISSUE 9). Both arms start from cleared jax
    caches so each pays its honest cold path; the AOT arm additionally
    witnesses bit-identical scores against the in-process build."""
    import shutil
    import tempfile

    import jax as _jax

    from ddt_tpu import api
    from ddt_tpu.backends import get_backend as _get_backend
    from ddt_tpu.registry.loader import load_servable, push_servable
    from ddt_tpu.serve.engine import ServableModel, default_buckets

    _be, Xb, ens = _predict_setup(4 * max_batch, features, bins, trees,
                                  depth, seed, backend=backend)
    del _be
    bundle = api.ModelBundle(ensemble=ens, mapper=None)
    root = tempfile.mkdtemp(prefix="ddt_reg_bench_")
    try:
        push_servable(root, bundle, name="bench", max_batch=max_batch,
                      quantize=quantize)
        cold_cfg = TrainConfig(backend=backend, n_bins=bins,
                               predict_impl="lut" if quantize else "auto")

        _jax.clear_caches()
        t0 = time.perf_counter()
        rebuild = ServableModel(
            bundle, _get_backend(cold_cfg, use_cache=False),
            quantize=quantize, buckets=default_buckets(max_batch))
        rebuild.warmup()
        rebuild_ms = (time.perf_counter() - t0) * 1e3
        want = rebuild.score_binned(Xb[:max_batch])

        _jax.clear_caches()
        t0 = time.perf_counter()
        report = load_servable(root, "bench", quantize=quantize)
        report.model.warmup()
        aot_ms = (time.perf_counter() - t0) * 1e3
        got = report.model.score_binned(Xb[:max_batch])
        if report.mode.startswith("aot") and not np.array_equal(want, got):
            raise AssertionError(
                "registry-restored scores diverge from the in-process "
                "build — the bit-exactness contract broke")
        return {
            "kernel": "registry_cold_load", "backend": backend,
            "trees": trees, "depth": depth, "features": features,
            "max_batch": max_batch, "quantized": bool(quantize),
            "mode": report.mode,
            "registry_rebuild_cold_ms": round(rebuild_ms, 3),
            "registry_aot_cold_ms": round(aot_ms, 3),
            "registry_aot_speedup": round(rebuild_ms / aot_ms, 3)
            if aot_ms > 0 else None,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_bench(kernel: str = "histogram", **kw) -> dict:
    # None-valued kwargs defer to each bench fn's own default — the CLI
    # passes --features=None unless the user set it, so the wide-shape
    # kernels (hist_2d: F=1024) keep their documented defaults instead
    # of inheriting a narrow-arm constant.
    kw = {k: v for k, v in kw.items() if v is not None}
    if kernel == "histogram":
        keys = ("backend", "rows", "features", "bins", "iters",
                "partitions", "hist_impl", "seed", "reps")
        return bench_histogram(**{k: kw[k] for k in keys if k in kw})
    if kernel == "train":
        keys = ("backend", "rows", "features", "bins", "trees", "depth",
                "partitions", "hist_impl", "seed")
        return bench_train(**{k: kw[k] for k in keys if k in kw})
    if kernel == "predict":
        keys = ("backend", "rows", "features", "bins", "trees", "depth",
                "partitions", "seed")
        return bench_predict(**{k: kw[k] for k in keys if k in kw})
    if kernel == "serve":
        keys = ("backend", "rows", "features", "bins", "trees", "depth",
                "seed")
        return bench_serve_latency(**{k: kw[k] for k in keys if k in kw})
    if kernel == "registry":
        keys = ("backend", "features", "bins", "trees", "depth", "seed")
        return bench_registry_cold_load(
            **{k: kw[k] for k in keys if k in kw})
    if kernel == "hist_comms":
        keys = ("rows", "features", "bins", "depth", "iters", "seed")
        return bench_hist_comms_ab(**{k: kw[k] for k in keys if k in kw})
    if kernel == "hist_2d":
        keys = ("rows", "features", "bins", "depth", "iters", "seed")
        return bench_hist_2d(**{k: kw[k] for k in keys if k in kw})
    if kernel == "hist_quant":
        keys = ("rows", "features", "bins", "depth", "iters", "seed",
                "grad_dtype")
        return bench_hist_quant_ab(**{k: kw[k] for k in keys if k in kw})
    if kernel == "lut4":
        keys = ("rows", "features", "bins", "trees", "depth", "seed")
        return bench_predict_lut4_ab(
            **{k: kw[k] for k in keys if k in kw})
    raise ValueError(f"unknown bench kernel {kernel!r}")
