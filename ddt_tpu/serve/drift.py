"""Inference-traffic drift observatory (ISSUE 19).

Two trackers, both leaf objects owned by a fleet slot:

* `DriftTracker` — accumulates per-feature bin histograms of the
  already-binned uint8 rows the dispatcher scores (the quantizer owns
  the bin space, so the dispatched `Xb` IS the histogram input — no
  float math on the request path) and scores the rolling window against
  the artifact's training reference (`BinMapper.ref_counts`) with two
  divergences: PSI (population stability index, the industry drift
  score) and Jensen-Shannon (bounded [0,1], base 2). Alerts are LATCHED
  transitions like SLO breaches: crossing the PSI threshold fires once
  and re-arms only after recovery. The alert payload is buffered in
  `_pending` — handler threads flush it into the run log via the
  fleet's `_flush_events` seam; the dispatcher never does file I/O.

* `ShadowScorer` — champion/challenger shadow mode. A dedicated daemon
  thread re-scores the SAME dispatched batches on the challenger model
  OFF the response path: the dispatcher enqueues (rows, champion
  scores) into a small drop-on-full ring and moves on, so a slow
  challenger can never stretch the champion's tail (drops are counted
  and surfaced — shadow comparison is a statistical sample, not an
  audit log). Tracks online prediction divergence (mean |champion -
  challenger|) and the challenger's own scoring latency.

Window memory is bounded by construction: the drift window is a ring of
`N_SLICES` coarse time slices of summed counts (rotated in O(1) per
observe), not a deque of per-batch histograms — the express lane emits
thousands of single-row batches per second and each raw [F, n_bins]
counts matrix is tens of KiB. Resolution is window_s / N_SLICES; the
window length is therefore quantized to one slice.

Thread model: both locks are leaves — nothing is called while they are
held, so they order after every fleet/batcher lock trivially.
"""

from __future__ import annotations

import threading

import numpy as np

from ddt_tpu.data.quantizer import feature_bincounts

#: rolling-window defaults (DriftTracker): a 5-minute window sliced
#: into 16 rotating buckets (~19 s resolution), scored only once it
#: holds MIN_ROWS rows (below that the estimate is noise — the state
#: surfaces None, omit-don't-lie like the SLO burn rate).
WINDOW_S = 300.0
N_SLICES = 16
MIN_ROWS = 256
#: the conventional PSI alert threshold: < 0.1 stable, 0.1-0.25
#: moderate shift, >= 0.25 significant shift (the alert).
PSI_ALERT = 0.25
#: additive smoothing applied to BOTH distributions at scoring time so
#: an empty bin on either side cannot produce log(0) — the reference
#: rides raw counts precisely so the scorer owns this choice.
EPS = 1e-6


def _smooth(counts: np.ndarray, totals: np.ndarray) -> np.ndarray:
    """Raw per-feature counts [F, B] -> smoothed probabilities."""
    b = counts.shape[1]
    return (counts + EPS) / (totals[:, None] + b * EPS)


def divergence(ref_counts: np.ndarray,
               win_counts: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-feature (PSI, JS) of a window histogram against the
    reference, both [F]. PSI = sum((q-p) * ln(q/p)) over bins;
    JS = Jensen-Shannon divergence in base 2 (bounded [0, 1]). The ONE
    divergence home — the tracker, tests, and the smoke arm's offline
    recompute all call it."""
    ref_counts = np.asarray(ref_counts, np.float64)
    win_counts = np.asarray(win_counts, np.float64)
    p = _smooth(ref_counts, ref_counts.sum(axis=1))
    q = _smooth(win_counts, win_counts.sum(axis=1))
    psi = ((q - p) * np.log(q / p)).sum(axis=1)
    m = 0.5 * (p + q)
    js = 0.5 * ((p * np.log2(p / m)).sum(axis=1)
                + (q * np.log2(q / m)).sum(axis=1))
    return psi, js


class DriftTracker:
    """Rolling-window per-feature divergence of dispatched traffic
    against a training reference histogram. All methods are cheap,
    lock-scoped host math (no I/O, no device): `observe` runs on the
    dispatcher per batch; `state`/`per_feature`/`take_pending` on
    handler threads."""

    def __init__(self, ref_counts, *, window_s: float = WINDOW_S,
                 min_rows: int = MIN_ROWS, threshold: float = PSI_ALERT):
        ref = np.asarray(ref_counts, np.int64)
        if ref.ndim != 2:
            raise ValueError(
                f"ref_counts must be [n_features, n_bins], got {ref.shape}")
        self._ref = ref
        self.n_features = int(ref.shape[0])
        self.n_bins = int(ref.shape[1])
        self.window_s = float(window_s)
        self.min_rows = int(min_rows)
        self.threshold = float(threshold)
        self._lock = threading.Lock()
        # Time-sliced ring of summed counts: bounded memory no matter
        # the batch rate (module doc). _win/_win_rows are the running
        # window sums, maintained incrementally on rotate.
        self._slices = np.zeros((N_SLICES, self.n_features, self.n_bins),
                                np.int64)
        self._slice_rows = np.zeros(N_SLICES, np.int64)
        self._win = np.zeros((self.n_features, self.n_bins), np.int64)
        self._win_rows = 0
        self._t0 = None            # first-observe anchor
        self._abs_slice = 0        # absolute slice index of the cursor
        self._alerting = False
        self._alerts = 0
        self._pending: list = []   # alert payloads awaiting a handler flush

    # -- ring rotation (call with _lock held) -------------------------- #
    def _rotate_locked(self, now: float) -> None:
        if self._t0 is None:
            self._t0 = now
            return
        span = self.window_s / N_SLICES
        target = int(max(0.0, now - self._t0) / span)
        steps = target - self._abs_slice
        if steps <= 0:
            return
        if steps >= N_SLICES:
            self._slices[:] = 0
            self._slice_rows[:] = 0
            self._win[:] = 0
            self._win_rows = 0
        else:
            for s in range(self._abs_slice + 1, target + 1):
                i = s % N_SLICES
                self._win -= self._slices[i]
                self._win_rows -= int(self._slice_rows[i])
                self._slices[i] = 0
                self._slice_rows[i] = 0
        self._abs_slice = target

    def _scores_locked(self) -> "tuple | None":
        if self._win_rows < self.min_rows:
            # An unscorable window ends the alert episode: holding the
            # latch with no evidence would pair alerting=True with
            # psi_max=None in /healthz — fresh drift after a traffic
            # gap is a NEW episode (a new alert), like an SLO re-breach
            # after the fast window cools.
            self._alerting = False
            return None
        return divergence(self._ref, self._win)

    # -- dispatcher side ------------------------------------------------ #
    def observe(self, now: float, Xb: np.ndarray) -> "dict | None":
        """Fold one dispatched uint8 batch into the window and score it.
        Returns the alert payload on a latched False->True transition of
        (max per-feature PSI >= threshold), else None; the same payload
        is buffered for the handler-thread event flush."""
        counts = feature_bincounts(Xb, self.n_bins)
        with self._lock:
            self._rotate_locked(now)
            i = self._abs_slice % N_SLICES
            self._slices[i] += counts
            self._slice_rows[i] += len(Xb)
            self._win += counts
            self._win_rows += len(Xb)
            scores = self._scores_locked()
            if scores is None:
                return None
            psi, js = scores
            psi_max = float(psi.max())
            alerting = psi_max >= self.threshold
            alert = None
            if alerting and not self._alerting:
                self._alerts += 1
                f = int(psi.argmax())
                alert = {
                    "psi_max": round(psi_max, 4),
                    "js_max": round(float(js.max()), 4),
                    "psi_mean": round(float(psi.mean()), 4),
                    "feature": f,
                    "window_rows": int(self._win_rows),
                    "window_s": self.window_s,
                    "threshold": self.threshold,
                    "alerts": self._alerts,
                }
                self._pending.append(alert)
            self._alerting = alerting
            return alert

    # -- handler side ---------------------------------------------------- #
    def state(self, now: float) -> dict:
        """Current window scores for /healthz + /metrics. Divergence
        keys are None under min_rows (omit, don't lie)."""
        with self._lock:
            self._rotate_locked(now)
            scores = self._scores_locked()
            out = {
                "window_rows": int(self._win_rows),
                "window_s": self.window_s,
                "threshold": self.threshold,
                "alerting": self._alerting,
                "alerts": self._alerts,
                "psi_max": None, "psi_mean": None,
                "js_max": None, "feature": None,
            }
            if scores is not None:
                psi, js = scores
                out.update(
                    psi_max=round(float(psi.max()), 4),
                    psi_mean=round(float(psi.mean()), 4),
                    js_max=round(float(js.max()), 4),
                    feature=int(psi.argmax()))
            return out

    def per_feature(self, now: float) -> "list | None":
        """Per-feature attribution for GET /debug/drift: [{feature,
        psi, js, window_rows}] sorted worst-first, or None under
        min_rows."""
        with self._lock:
            self._rotate_locked(now)
            scores = self._scores_locked()
            if scores is None:
                return None
            psi, js = scores
            rows = self._win.sum(axis=1)
            out = [{"feature": f, "psi": round(float(psi[f]), 4),
                    "js": round(float(js[f]), 4),
                    "window_rows": int(rows[f])}
                   for f in range(self.n_features)]
            out.sort(key=lambda r: -r["psi"])
            return out

    def has_pending(self) -> bool:
        # Unlocked truthiness read (same idiom as SloBurnTracker): worst
        # case a flush runs one hot-path call late.
        return bool(self._pending)

    def take_pending(self) -> list:
        with self._lock:
            out, self._pending = self._pending, []
            return out


class ShadowScorer:
    """Challenger shadow scoring off the response path (module doc).
    `enqueue` is the dispatcher side: O(1), drop-on-full, never blocks.
    The scorer thread reads the challenger slot's CURRENT model
    reference — an evicted challenger skips batches (counted) rather
    than triggering a load from this thread."""

    QUEUE_CAP = 4
    MS_RING = 1024

    def __init__(self, name: str, champion: str, slot, clock):
        self.name = name              # challenger model name
        self.champion = champion
        self._slot = slot             # the challenger's FleetSlot
        self._clock = clock
        self._cv = threading.Condition()
        self._q: list = []
        self._closed = False
        self._rows = 0
        self._diff_sum = 0.0          # sum of |delta| over compared rows
        self._diff_rows = 0
        self._ms: list = []           # challenger per-batch scoring ms
        self._dropped = 0
        self._skipped = 0             # challenger not resident
        self._errors = 0
        self._thread = threading.Thread(
            target=self._run, name=f"ddt-shadow-{name}", daemon=True)
        self._thread.start()

    # -- dispatcher side ------------------------------------------------ #
    def enqueue(self, Xb, scores) -> None:
        with self._cv:
            if self._closed:
                return
            if len(self._q) >= self.QUEUE_CAP:
                self._dropped += 1
                return
            self._q.append((Xb, scores))
            self._cv.notify()

    # -- scorer thread --------------------------------------------------- #
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(timeout=1.0)
                if not self._q:
                    if self._closed:
                        return
                    continue
                Xb, champ_scores = self._q.pop(0)
            model = self._slot.model
            if model is None:
                with self._cv:
                    self._skipped += 1
                continue
            t0 = self._clock()
            try:
                mine = np.asarray(model.score_binned(Xb), np.float64)
            except Exception:  # ddtlint: disable=broad-except
                # A challenger failure must never take the scorer thread
                # down — it is an observer, not a participant.
                with self._cv:
                    self._errors += 1
                continue
            ms = (self._clock() - t0) * 1e3
            champ = np.asarray(champ_scores, np.float64)
            diff = (float(np.abs(mine - champ).mean())
                    if mine.shape == champ.shape else None)
            with self._cv:
                self._rows += len(Xb)
                if diff is not None:
                    self._diff_sum += diff * len(Xb)
                    self._diff_rows += len(Xb)
                self._ms.append(ms)
                if len(self._ms) > self.MS_RING:
                    del self._ms[: len(self._ms) - self.MS_RING]

    # -- handler side ---------------------------------------------------- #
    def summary(self) -> dict:
        """Online comparison stats for /healthz, /debug/drift, and the
        serve_latency shadow extras. mean_abs_diff/ms_p50 are None until
        the challenger has actually scored something."""
        with self._cv:
            ms = sorted(self._ms)
            return {
                "model": self.name,
                "champion": self.champion,
                "rows": self._rows,
                "mean_abs_diff": (
                    round(self._diff_sum / self._diff_rows, 6)
                    if self._diff_rows else None),
                "ms_p50": (round(ms[len(ms) // 2], 3) if ms else None),
                "dropped": self._dropped,
                "skipped": self._skipped,
                "errors": self._errors,
            }

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
