"""Stdlib HTTP front end for the ServeEngine (`cli serve`).

Deliberately dependency-free (http.server + json): the serving tier's
value is the engine (admission batching, device-resident models,
hot-swap, SLO telemetry) — the transport is a thin adapter any real
deployment would replace (gRPC, a sidecar, an in-process embedding).
Threading model: ThreadingHTTPServer gives one thread per connection;
each handler thread is a SUBMITTER into the engine's admission queue,
so concurrent HTTP requests coalesce into micro-batches exactly like
library callers (scripts/serve_smoke.py drives 100 of them).

Endpoints (all JSON):

- POST /predict   {"rows": [[...], ...], "binned": false}
                  -> {"scores": [...], "model": token}
- POST /predict?binned=raw   ZERO-COPY binned wire path (ISSUE 12):
                  the body IS the uint8 row block (Content-Type
                  application/octet-stream, Content-Length required =
                  n_rows * n_features bytes). The bytes go wire ->
                  np.frombuffer view -> device untouched — no float
                  parse, no re-bin, no JSON; the LUT kernels stream
                  raw uint8, so a single-row request's payload is F
                  bytes end to end. Bounds are structural (a byte IS a
                  valid bin id, the same 0..255 domain the JSON binned
                  path range-checks); a body that is not a whole
                  number of rows is rejected 400 loudly.
- POST /swap      {"model": "/path/to/model.npz"} — or a REGISTRY
                  reference {"model": "name@version" | "name@tag" |
                  "<digest>"} when the server was started with
                  `cli serve --registry` (docs/REGISTRY.md): the
                  artifact restores through the zero-retrace loader,
                  digest-verified, off the request path.
                  -> {"old": token, "new": token}   (zero-downtime)
- GET  /healthz   -> engine.health() (+ all-time latency snapshot)
- GET  /stats     -> current-window latency summary; "?emit=1" also
                  emits it as a run-log `serve_latency` event and
                  resets the window
- GET  /metrics   Prometheus-style text exposition (ISSUE 17):
                  process counters, per-model cumulative latency
                  histograms on the fixed bucket ladder, live
                  backlog/residency gauges, SLO objective + burn rate.
                  STRICTLY read-only — a scrape never resets a window
                  or emits an event (that is /stats?emit=1's job).
- GET  /debug/requests   {"models": {name: [last-N trace records]}} —
                  the per-model ring of completed request traces;
                  "?emit=1" also flushes the rings into the run log as
                  `serve_trace` events.
- GET  /debug/drift   (fleet servers, ISSUE 19) the drift observatory:
                  per-model rolling-window divergence state (PSI / JS
                  against the training reference), worst-first
                  per-feature attribution, and champion/challenger
                  shadow comparison (docs/OBSERVABILITY.md "Drift
                  observatory").
- POST /shutdown  -> drains and stops the server

TRACE PROPAGATION (ISSUE 17): every /predict response carries
`X-DDT-Trace-Id` (the client's request header of the same name is
honored, else a server-minted id) and `X-DDT-Timing` — the per-request
breakdown `handler=...,queue=...,gate=...,device=...,wake=...,
total=...` (ms; ddt_tpu/serve/batcher.py `trace_breakdown` is the
shape home). Disabled with `cli serve --no-request-traces`, in which
case a client-supplied id is still echoed back (propagation without
measurement).

FLEET servers (`cli serve --models/--fleet-config`, ISSUE 15 —
docs/SERVING.md "Fleet") add per-model routing and a control plane:

- POST /models/<name>/predict   route by URL path (binned=raw works
                  here too — the raw body decodes against THAT
                  model's width, reloading it on this handler thread
                  if it was evicted);
- POST /predict + header `X-DDT-Model: <name>`   route by header;
- GET  /models                 the fleet table (residency, weights,
                  tiers, eviction/reload counts, queue depths);
- POST /models    {"action": "add"|"remove"|"retag", ...} — mutate
                  the fleet without restart (add takes a fleet-config
                  entry; retag takes {"name", "ref"[, "tier"]});
- GET  /models/<name>/stats    that model's current window;
- GET  /stats[?emit=1]         every model's windows (emit = one
                  serve_latency event per model, model_name stamped).

An unknown model name is a STRUCTURED 404 ({"error", "model",
"models"}); a model whose eviction-reload fails is a structured 503
({"error", "model", "reason"}) — never a bare 500 from the handler
thread (the ISSUE 15 bugfix). /swap on a fleet is a 400 pointing at
POST /models.

File I/O note: model loading (api.load_model) happens HERE, on the
swap/boot path — never in the engine or batcher hot-loop modules (the
ddtlint serve-blocking-io rule).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ddt_tpu.serve.batcher import ShuttingDown, trace_breakdown
from ddt_tpu.serve.fleet import ModelUnavailableError, UnknownModelError
from ddt_tpu.serve.metrics import render_metrics
from ddt_tpu.telemetry import counters as tele_counters

log = logging.getLogger("ddt_tpu.serve.http")

#: request header that routes a /predict to a fleet model (the URL
#: path form is /models/<name>/predict — both work, binned=raw
#: included).
MODEL_HEADER = "X-DDT-Model"

#: trace propagation headers (module doc): the id rides the request in
#: and the response out; the timing breakdown rides the response only.
TRACE_HEADER = "X-DDT-Trace-Id"
TIMING_HEADER = "X-DDT-Timing"

#: X-DDT-Timing segment order (the trace_breakdown keys, ms suffix
#: stripped on the wire: handler=0.012,queue=1.403,...,total=4.791).
_TIMING_KEYS = ("handler_ms", "queue_ms", "gate_ms", "device_ms",
                "wake_ms", "total_ms")


def format_timing(breakdown: "dict | None") -> "str | None":
    """trace_breakdown dict -> the X-DDT-Timing header value."""
    if breakdown is None:
        return None
    return ",".join(f"{k[:-3]}={breakdown[k]}" for k in _TIMING_KEYS)


def _swap(engine, ref: str) -> dict:
    """Resolve a /swap target — an artifact path on disk, or (when the
    engine carries a registry root) a registry reference — build + warm
    the new model on THIS handler thread, and publish it. An existing
    file always wins; anything else needs `--registry`, so a mistyped
    path fails loudly instead of being treated as a model name."""
    import os

    if os.path.exists(ref):
        from ddt_tpu import api

        return engine.swap(api.load_model(ref))
    registry_root = getattr(engine, "registry_root", None)
    if registry_root is None:
        raise ValueError(
            f"{ref!r} is not a file, and this server was started "
            "without --registry so registry references cannot resolve")
    from ddt_tpu.registry import loader as reg_loader

    # The engine's serving mode wins: a quantized server stays on its
    # TIER (missing LUT export -> loud 400), an f32 server serves the
    # f32 variant even from a quantized artifact.
    report = reg_loader.load_servable(
        registry_root, ref,
        quantize=engine.quantize_tier if engine.quantize else False,
        raw=engine.raw, backend=engine.backend,
        run_log=engine.run_log)
    out = engine.swap(report.model)
    out["artifact_digest"] = report.digest
    out["mode"] = report.mode
    return out


def decode_raw_rows(body: bytes, n_features: int,
                    declared_len: "int | None") -> np.ndarray:
    """`binned=raw` wire decode: the body IS the uint8 row block.

    Zero-copy by construction — np.frombuffer wraps the received bytes
    and the reshape is a view, so the array handed to the engine (and
    from there to the device upload) is the wire buffer itself. The
    checks are exactly once and O(1): Content-Length must be declared
    and match what arrived (a truncated body must not become fewer
    rows), and the byte count must be a whole number of `n_features`-
    wide rows (a width mismatch is a 400, never a silent reshape).
    Bin-id bounds are structural: a byte cannot leave [0, 255], the
    same domain the JSON binned path range-checks value by value."""
    if declared_len is None:
        raise ValueError(
            "binned=raw requires a Content-Length header (the row "
            "block is validated against it before it touches the "
            "engine)")
    if len(body) != declared_len:
        raise ValueError(
            f"binned=raw body is {len(body)} bytes but Content-Length "
            f"declared {declared_len}")
    if len(body) == 0:
        raise ValueError("binned=raw body is empty")
    if len(body) % n_features:
        raise ValueError(
            f"binned=raw body of {len(body)} bytes is not a whole "
            f"number of {n_features}-feature rows")
    return np.frombuffer(body, dtype=np.uint8).reshape(-1, n_features)


def _models_post(engine, req: dict) -> dict:
    """POST /models control plane (fleet servers only): add / remove /
    retag without restart. The spec coercion reuses the fleet-config
    grammar, so the wire and the config file cannot drift."""
    import dataclasses

    from ddt_tpu.serve import control as fleet_control

    action = req.get("action")
    if action == "add":
        d = {k: v for k, v in req.items() if k != "action"}
        return engine.add_model(
            fleet_control.coerce_spec(d, "POST /models add"))
    if action == "remove":
        if "name" not in req:
            raise ValueError("POST /models remove needs a 'name'")
        return engine.remove_model(req["name"])
    if action == "retag":
        if "name" not in req or "ref" not in req:
            raise ValueError(
                "POST /models retag needs 'name' and 'ref' (the new "
                "registry reference the model should serve)")
        spec = dataclasses.replace(engine.spec_for(req["name"]),
                                   ref=str(req["ref"]))
        if "tier" in req:
            from ddt_tpu.serve.engine import normalize_quantize

            spec = dataclasses.replace(
                spec, tier=normalize_quantize(req["tier"]))
        return engine.retag(req["name"], spec)
    raise ValueError(
        f"POST /models: unknown action {action!r} (expected add, "
        "remove, or retag)")


def _unknown_model_body(e: UnknownModelError) -> dict:
    """The ONE structured 404 body for an unaddressable model (shared
    by the GET and POST error boundaries — the two surfaces cannot
    drift)."""
    return {"error": str(e), "model": e.name, "models": e.known}


def _make_handler(engine, server_box: dict):
    fleet = bool(getattr(engine, "fleet", False))

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # route through logging
            log.debug("%s " + fmt, self.address_string(), *args)

        def _send(self, code: int, payload: dict,
                  headers: "dict | None" = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n) if n else b"{}"
            return json.loads(raw or b"{}")

        def _route_model(self) -> "tuple[str, str | None]":
            """Split the URL into (root path, routed model name):
            `/models/<name>/predict` routes by path, anything else by
            the X-DDT-Model header (None = unrouted). A routed request
            against a single-model server is a structured 404 — the
            fleet surface simply does not exist there (the ISSUE 15
            bugfix: never a bare 500 for an unroutable request)."""
            path = self.path.split("?", 1)[0]
            name = self.headers.get(MODEL_HEADER)
            if path.startswith("/models/"):
                parts = path.split("/", 3)
                if len(parts) == 4:
                    name = parts[2]
                    path = "/" + parts[3]
            if name is not None and not fleet:
                raise UnknownModelError(name, [])
            return path, name

        def do_GET(self):
            try:
                path, name = self._route_model()
                if path == "/healthz":
                    return self._send(200, engine.health())
                if path == "/metrics":
                    # Read-only by contract: both snapshot calls only
                    # READ (counters.snapshot copies, metrics_snapshot
                    # renders live state) — no window reset, no emit.
                    return self._send_text(200, render_metrics(
                        tele_counters.snapshot(),
                        engine.metrics_snapshot()))
                if path == "/debug/requests":
                    out = {"models": engine.debug_traces()}
                    if "emit=1" in self.path:
                        out["flushed"] = engine.flush_traces(
                            reason="on_demand")
                    return self._send(200, out)
                if path == "/debug/drift" and fleet:
                    # Handler thread: debug_drift flushes any pending
                    # drift events on the way (file I/O lives here,
                    # never on the dispatcher).
                    return self._send(200, engine.debug_drift())
                if path == "/models" and fleet:
                    return self._send(200, {"models": engine.models()})
                if path == "/stats":
                    emit = "emit=1" in self.path
                    if fleet:
                        if name is not None:
                            # Unknown names are the same structured
                            # 404 as /predict — a monitoring typo must
                            # not read healthy zeros forever.
                            engine.spec_for(name)
                        if emit:
                            # Per-model emit resets ONLY that model's
                            # window (`only=`); the unrouted form
                            # emits every model.
                            out = engine.emit_latency(reset=True,
                                                      only=name)
                        else:
                            out = engine.window_summaries(reset=False)
                        if name is not None:
                            out = out.get(name) or {"requests": 0,
                                                    "model_name": name}
                        return self._send(200, out)
                    if emit:
                        out = engine.emit_latency(reset=True) or {
                            "requests": 0}
                    else:
                        out = engine.stats.window_summary(reset=False)
                    return self._send(200, out)
                return self._send(404,
                                  {"error": f"no route {self.path}"})
            except UnknownModelError as e:
                return self._send(404, _unknown_model_body(e))

        def do_POST(self):
            try:
                path, name = self._route_model()
                if path == "/predict":
                    qs = self.path.partition("?")[2]
                    ctype = self.headers.get("Content-Type", "")
                    if ("binned=raw" in qs.split("&")
                            or ctype.startswith(
                                "application/octet-stream")):
                        # Zero-copy binned wire path (module doc): the
                        # body bytes become the row array directly —
                        # width derived from the routed model (a swap
                        # race is caught again at dispatch, like every
                        # other request). On a fleet this may reload an
                        # evicted model HERE, on the handler thread —
                        # never the dispatcher's.
                        n = self.headers.get("Content-Length")
                        declared = int(n) if n is not None else None
                        if declared is not None and declared < 0:
                            # read(-1) would block to EOF on a
                            # keep-alive socket — reject before reading.
                            raise ValueError(
                                "binned=raw Content-Length must be "
                                f">= 0, got {declared}")
                        body = self.rfile.read(declared) \
                            if declared else b""
                        width = (engine.n_features_for(name) if fleet
                                 else engine.n_features)
                        rows = decode_raw_rows(body, width, declared)
                    else:
                        req = self._body()
                        rows = np.asarray(req["rows"])
                        if req.get("binned"):
                            # astype(uint8) would silently WRAP
                            # out-of-range ids (300 -> 44) and truncate
                            # floats — fail the contract violation
                            # loudly like every other malformed input
                            # in this handler.
                            if rows.dtype.kind not in "iu" or (
                                    rows.size and (int(rows.min()) < 0
                                                   or int(rows.max())
                                                   > 255)):
                                raise ValueError(
                                    "binned rows must be integer bin "
                                    "ids in 0..255")
                            rows = rows.astype(np.uint8)
                    # The dispatcher stamps the token of the model that
                    # ACTUALLY scored the batch — reading engine.
                    # model_token here instead races the hot swap and
                    # mis-attributes responses that straddle it.
                    trace_id = self.headers.get(TRACE_HEADER)
                    if fleet:
                        pending = engine.predict_async(
                            rows, model=name, trace_id=trace_id)
                    else:
                        pending = engine.predict_async(
                            rows, trace_id=trace_id)
                    scores = pending.result(30.0)
                    headers = {}
                    if pending.trace_id is not None:
                        headers[TRACE_HEADER] = pending.trace_id
                        timing = format_timing(trace_breakdown(pending))
                        if timing is not None:
                            headers[TIMING_HEADER] = timing
                    return self._send(200, {
                        "scores": np.asarray(scores).tolist(),
                        "model": pending.model_token},
                        headers=headers)
                if path == "/models" and fleet:
                    return self._send(200,
                                      _models_post(engine, self._body()))
                if path == "/swap":
                    if fleet:
                        raise ValueError(
                            "fleet servers manage models via POST "
                            "/models (action add/remove/retag), not "
                            "/swap")
                    req = self._body()
                    return self._send(200, _swap(engine, req["model"]))
                if path == "/shutdown":
                    self._send(200, {"ok": True})
                    threading.Thread(
                        target=server_box["server"].shutdown,
                        daemon=True).start()
                    return None
                return self._send(404, {"error": f"no route {self.path}"})
            # The handler IS the error boundary: every failure must
            # become a JSON response on the open connection, never an
            # unwound handler (= connection reset with no body). Order
            # matters: TimeoutError is an OSError subclass, and the
            # fleet routing errors subclass KeyError/RuntimeError — the
            # STRUCTURED 404/503 bodies must win over the generic
            # 400/500 (the ISSUE 15 bugfix: an unknown or
            # evicted-and-reload-failing model is an addressed,
            # machine-readable refusal, not a bare 500).
            except TimeoutError as e:
                return self._send(504, {"error": f"{type(e).__name__}: "
                                                 f"{e}"})
            except ShuttingDown as e:
                return self._send(503, {"error": f"{type(e).__name__}: "
                                                 f"{e}"})
            except UnknownModelError as e:
                return self._send(404, _unknown_model_body(e))
            except ModelUnavailableError as e:
                return self._send(503, {
                    "error": str(e), "model": e.name,
                    "reason": e.reason})
            except (KeyError, ValueError, TypeError, OSError) as e:
                return self._send(400, {"error": f"{type(e).__name__}: "
                                                 f"{e}"})
            # Dispatch-delivered failures (a scoring error re-raised by
            # result()) can be anything; surfaced as 500, re-raising
            # would just tear the connection down bodyless.
            except Exception as e:  # ddtlint: disable=broad-except
                return self._send(500, {"error": f"{type(e).__name__}: "
                                                 f"{e}"})

    return Handler


def serve_forever(engine, host: str = "127.0.0.1", port: int = 8199,
                  ready_event: "threading.Event | None" = None) -> int:
    """Run the HTTP front end until /shutdown (or KeyboardInterrupt);
    returns the BOUND port (pass port=0 for an ephemeral one — the
    smoke test does). `ready_event` is set once the socket listens."""
    box: dict = {}

    class _Server(ThreadingHTTPServer):
        # The default socketserver backlog (5) resets connections under
        # exactly the burst concurrency admission batching exists for —
        # a 100-way storm must QUEUE at the socket, not fail
        # (scripts/serve_smoke.py drives this).
        request_queue_size = 128
        daemon_threads = True

    server = _Server((host, port), _make_handler(engine, box))
    box["server"] = server
    bound = server.server_address[1]
    # Published BEFORE ready_event fires so a launcher thread can learn
    # an ephemeral (port=0) binding without racing serve_forever's
    # blocking loop (scripts/serve_smoke.py).
    engine.http_port = bound
    if getattr(engine, "fleet", False):
        log.info("serving fleet on %s:%d (%d model(s))", host, bound,
                 len(engine.models()))
    else:
        log.info("serving on %s:%d (model %s)", host, bound,
                 engine.model_token[:12])
    if ready_event is not None:
        ready_event.set()
    try:
        server.serve_forever(poll_interval=0.05)
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        engine.close()
    return bound
