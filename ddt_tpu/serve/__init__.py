"""Low-latency serving tier (docs/SERVING.md).

`ServeEngine` holds device-resident compiled models behind an
admission-batching request queue (`MicroBatcher`): concurrent small
requests coalesce into micro-batches that ride a fixed set of
pre-traced bucket shapes, models hot-swap atomically keyed on the
content-digest cache token, and per-request latency lands in the run
log as the schema-v4 `serve_latency` event. The int8 TreeLUT fast path
(ops/predict_lut.py) is the `quantize=True` opt-in. The HTTP front end
(`ddt_tpu.serve.http`, `cli serve`) is a thin stdlib adapter over the
same engine the tests and bench drive in-process.
"""

from ddt_tpu.serve.batcher import (MicroBatcher, PendingRequest,
                                   ShuttingDown)
from ddt_tpu.serve.engine import (ServableModel, ServeEngine, ServeStats,
                                  bucket_for, default_buckets,
                                  dispatch_batch, proba_np)
from ddt_tpu.serve.fleet import (FleetEngine, ModelUnavailableError,
                                 UnknownModelError)

__all__ = [
    "MicroBatcher", "PendingRequest", "ShuttingDown",
    "ServableModel", "ServeEngine", "ServeStats", "FleetEngine",
    "ModelUnavailableError", "UnknownModelError",
    "bucket_for", "default_buckets", "dispatch_batch", "proba_np",
]
