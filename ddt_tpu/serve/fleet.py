"""FleetEngine: multi-model tenancy over one device (ISSUE 15).

The serving tier's fleet half (docs/SERVING.md "Fleet"). One engine
holds N registry-resolved models resident concurrently:

- **per-model admission queues** — every model gets its own
  `MicroBatcher` in DRIVEN mode (no thread of its own; all batchers
  share the fleet's ONE Condition), so per-model coalescing, the
  pinned-to-the-head admission deadline, and the express lane all
  carry over unchanged from the single-model engine;
- **a single dispatcher thread** running weighted deficit-round-robin
  over the queues: each cycle a model with backlog earns
  `weight x max_batch` rows of credit and dispatches micro-batches
  until the credit runs out — under saturation a weight-3 model gets
  ~3x the device time of a weight-1 model, and an idle model costs
  nothing. One dispatcher thread means the single-model invariants
  hold PER MODEL: the model reference for a batch is read once at
  admission (old-or-new-never-a-mix under reload/retag), and the
  per-model dispatch gate keeps express and batch dispatches from
  overlapping on the same model;
- **LRU eviction + zero-downtime reload** — with `max_resident` set,
  publishing model N+1 demotes the least-recently-used idle model to
  its artifact (a reference drop: the AOT artifact in the registry IS
  the demoted form — the zero-retrace loader makes reloading it a
  bounded cold-load, never a retrace on the dispatcher thread). The
  next request for an evicted model reloads it on the CALLER's thread
  (handler threads own file I/O, the serve-blocking-io contract) and
  then queues normally: eviction is invisible to clients except as
  one request's cold-load latency;
- **a control plane** — `add_model`/`remove_model`/`retag` mutate the
  fleet without restart (the HTTP front end's `POST /models`), and
  per-model `serve_latency` windows (model_name dimension), the
  `fleet_evictions`/`fleet_reloads` counters, and `fault` events
  (kind=fleet_eviction/fleet_reload) feed `cli report`'s fleet rollup.

HOT-LOOP MODULE (the ddtlint serve-blocking-io + thread-model rules):
no file I/O anywhere in here — model loading is the injected `loader`
callable's job (ddt_tpu/serve/control.py builds it over the registry),
and it is only ever invoked on caller/handler threads with no fleet
lock held.
"""

from __future__ import annotations

import collections
import threading
import time

from ddt_tpu.serve import drift as serve_drift
from ddt_tpu.serve.batcher import MicroBatcher, PendingRequest, ShuttingDown
from ddt_tpu.serve.engine import ServeStats, coerce_rows, dispatch_batch
from ddt_tpu.telemetry import counters as tele_counters


class UnknownModelError(KeyError):
    """Request routed to a model name the fleet does not serve — the
    HTTP layer renders this as a structured 404 (never a bare 500)."""

    def __init__(self, name, known=()):
        self.name = name
        self.known = sorted(known)
        super().__init__(name)

    def __str__(self):
        return (f"no model named {self.name!r} in the fleet "
                f"(serving: {', '.join(self.known) or 'none'})")


class ModelUnavailableError(RuntimeError):
    """The model exists but cannot serve right now (evicted and its
    reload failed, or the residency race could not settle) — the HTTP
    layer renders this as a structured 503."""

    def __init__(self, name, reason):
        self.name = name
        self.reason = reason
        super().__init__(f"model {name!r} is unavailable: {reason}")


class _EvictedInFlight(RuntimeError):
    """Internal: an express dispatch found its slot evicted between the
    residency check and the gate (a tiny race window) — the submit path
    catches this, reloads, and requeues, so the CLIENT never sees it."""


class SloBurnTracker:
    """Rolling multi-window burn-rate tracking for ONE fleet member's
    latency SLO (ISSUE 17). The objective is a per-request latency bound
    (FleetSpec.slo_p99_ms) with the p99's implied 1% violation
    allowance; the burn rate over a window is

        burn = (violating requests / window requests) / ALLOWANCE

    so burn 1.0 spends the error budget exactly, burn 2.0 spends it
    twice as fast (the SRE burn-rate convention, degenerated to
    request-count windows over the live latency stream). A breach is a
    LATCHED transition: it fires when every window with at least
    MIN_REQUESTS samples burns at or past BREACH_BURN — the multi-window
    AND is what keeps one slow cold-load from paging — and re-arms only
    after the fast window cools below 1.0, so a continuously burning
    model is ONE `slo_breach` event, not one per batch.

    Thread model: its OWN leaf lock, never held while any fleet lock is
    taken (dispatcher closures and handler threads both call in; the
    fleet may read `burn_rates()` while holding its Condition because
    the nesting is always fleet-lock -> tracker-lock, never reversed).
    Breach payloads are buffered here (`_pending`) and swept by
    handler-thread touchpoints — the dispatcher never does file I/O."""

    #: rolling windows, seconds — fast page-worthy window first.
    WINDOWS_S = (30.0, 300.0)
    #: the p99's violation allowance (1 - 0.99).
    ALLOWANCE = 0.01
    #: burn rate at/past which every qualifying window must sit to latch.
    BREACH_BURN = 2.0
    #: minimum requests in a window before its burn rate is trusted.
    MIN_REQUESTS = 20

    def __init__(self, objective_ms):
        self.objective_ms = float(objective_ms)
        self._lock = threading.Lock()
        self._batches = collections.deque()   # (t, n, n_violating)
        self._latched = False
        self._pending: list = []
        self.breaches = 0

    def _prune_locked(self, now) -> None:
        horizon = now - self.WINDOWS_S[-1]
        while self._batches and self._batches[0][0] < horizon:
            self._batches.popleft()

    def _window_stats_locked(self, now) -> dict:
        out = {}
        for w in self.WINDOWS_S:
            cutoff = now - w
            n = bad = 0
            for t, k, b in self._batches:
                if t >= cutoff:
                    n += k
                    bad += b
            out[w] = (n, bad)
        return out

    def _rate(self, n, bad):
        if n < self.MIN_REQUESTS:
            return None
        return (bad / n) / self.ALLOWANCE

    def record(self, now, latencies_ms) -> "dict | None":
        """Fold one dispatched batch in; on the transition INTO breach,
        buffer the event payload and return it (the caller bumps the
        process counter — a plain int add, safe on any thread)."""
        bad = sum(1 for v in latencies_ms if v > self.objective_ms)
        with self._lock:
            self._batches.append((now, len(latencies_ms), bad))
            self._prune_locked(now)
            stats = self._window_stats_locked(now)
            rates = {w: self._rate(n, b) for w, (n, b) in stats.items()}
            fast = rates[self.WINDOWS_S[0]]
            if self._latched:
                if fast is not None and fast < 1.0:
                    self._latched = False
                return None
            if any(r is None or r < self.BREACH_BURN
                   for r in rates.values()):
                return None
            self._latched = True
            self.breaches += 1
            n_fast = stats[self.WINDOWS_S[0]][0]
            breach = {"burn_rate": round(fast, 3),
                      "objective_ms": self.objective_ms,
                      "window_s": self.WINDOWS_S[0],
                      "requests": n_fast}
            self._pending.append(breach)
            return breach

    def burn_rates(self, now) -> dict:
        """{"30s": rate|None, ...} — None = not enough samples yet."""
        with self._lock:
            self._prune_locked(now)
            stats = self._window_stats_locked(now)
        return {f"{w:g}s": (None if r is None else round(r, 3))
                for w, r in ((w, self._rate(n, b))
                             for w, (n, b) in stats.items())}

    def has_pending(self) -> bool:
        # Unlocked truthiness read: a stale False only delays the flush
        # to the next touchpoint, a stale True costs one empty sweep.
        return bool(self._pending)

    def take_pending(self) -> list:
        with self._lock:
            out, self._pending[:] = list(self._pending), []
        return out


class FleetSlot:
    """One fleet member: its spec, admission queue, residency state,
    and telemetry. Pure state — the engine owns every transition (all
    mutable fields are touched under the fleet Condition, except
    `model`, which is a single-reference publish read once per
    dispatch, the hot-swap idiom)."""

    def __init__(self, spec):
        self.spec = spec
        self.name = spec.name
        self.weight = float(spec.weight)
        self.stats = ServeStats()
        # SLO burn tracking only when the spec declares an objective
        # (getattr: pre-ISSUE-17 spec objects have no slo_p99_ms field).
        objective = getattr(spec, "slo_p99_ms", None)
        self.slo = SloBurnTracker(objective) if objective else None
        # Drift observatory (ISSUE 19): the divergence tracker is armed
        # at LOAD time (the reference histogram lives in the artifact's
        # mapper) and survives evictions — the rolling window is about
        # the traffic, not the residency. `shadow` is the attached
        # challenger's scorer when THIS slot is a shadowed champion;
        # `observer` is the engine-bound dispatch_batch observer
        # closure (bound once in _make_slot_locked).
        self.drift = None            # serve_drift.DriftTracker | None
        self.shadow = None           # serve_drift.ShadowScorer | None
        self.observer = None
        self.model = None            # resident ServableModel | None
        self.loading = False
        self.load_error = None
        self.ever_resident = False
        self.last_used = 0           # fleet LRU clock (monotonic int)
        self.evictions = 0
        self.reloads = 0
        self.deficit = 0.0           # DRR credit, in rows
        self.batcher = None          # bound by FleetEngine._make_slot


class FleetEngine:
    """N models, one device, one dispatcher thread (module doc).

    `specs` is a sequence of fleet specs (ddt_tpu/serve/control.py's
    FleetSpec: name/ref/weight/tier/max_batch/raw); `loader(spec)` must
    return a warmed-or-warmable ServableModel — it is called on caller
    threads only, never on the dispatcher. `max_resident=None` keeps
    every model resident (no eviction). `autostart=False` +
    `start()` is the test seam for deterministic backlog setup;
    `on_dispatch(name, rows)` observes the dispatch order (fairness
    tests); `clock` is the injectable admission clock shared with every
    batcher."""

    #: the HTTP front end branches on this (fleet routing + /models).
    fleet = True

    def __init__(self, specs, loader, *, max_wait_ms: float = 1.0,
                 max_resident: "int | None" = None, run_log=None,
                 express_lane: bool = True, clock=None,
                 on_dispatch=None, autostart: bool = True,
                 request_traces: bool = True):
        from ddt_tpu.telemetry.events import RunLog

        if max_resident is not None and max_resident < 1:
            raise ValueError(
                f"max_resident must be >= 1, got {max_resident}")
        self._loader = loader
        self.max_wait_ms = float(max_wait_ms)
        self.max_resident = max_resident
        self.express_lane = bool(express_lane)
        self.request_traces = bool(request_traces)
        self.run_log = RunLog.coerce(run_log)
        self._clock = clock if clock is not None else time.perf_counter
        self._on_dispatch = on_dispatch
        self._cv = threading.Condition()
        self._slots: dict[str, FleetSlot] = {}
        self._order: list[str] = []      # DRR rotation; mutated under _cv
        self._rr = 0
        self._use_seq = 0
        self._closed = False
        # Lifecycle events the DISPATCHER settled (evictions on queue
        # drain): buffered here and flushed to the run log by the next
        # handler-thread touchpoint (health/emit_latency/reload) — the
        # dispatcher thread never does file I/O (serve-blocking-io).
        self._pending_events: list = []
        for spec in specs:
            if spec.name in self._slots:
                raise ValueError(
                    f"duplicate model name {spec.name!r} in the fleet")
            self._make_slot_locked(spec)
        self._thread = threading.Thread(
            target=self._loop, name="ddt-fleet-dispatcher", daemon=True)
        if autostart:
            self._thread.start()

    def start(self) -> None:
        """Start the dispatcher (only meaningful after
        `autostart=False` — the deterministic-backlog test seam)."""
        if not self._thread.is_alive():
            self._thread.start()

    # ------------------------------------------------------------------ #
    # slots & residency
    # ------------------------------------------------------------------ #

    def _make_slot_locked(self, spec) -> FleetSlot:
        slot = FleetSlot(spec)
        # DRIVEN batcher: shares the fleet Condition (one dispatcher
        # thread parks on every queue), and its express dispatch is a
        # slot-bound closure so the lane works exactly as on the
        # single-model engine — same gate, same error containment.
        slot.batcher = MicroBatcher(
            self._express_fn(slot), max_wait_ms=self.max_wait_ms,
            max_batch=spec.max_batch, clock=self._clock, cv=self._cv,
            own_thread=False, request_traces=self.request_traces)
        slot.observer = self._observer_fn(slot)
        self._slots[spec.name] = slot
        self._order.append(spec.name)
        self._wire_shadow_locked(slot)
        return slot

    def _wire_shadow_locked(self, slot: FleetSlot) -> None:
        """Attach challenger scorers for a just-created slot, in BOTH
        directions (a fleet config may list the shadow before or after
        its champion — boot order is free; control.validate_specs has
        already refused dangling or chained shadow_of)."""
        champ_name = getattr(slot.spec, "shadow_of", None)
        if champ_name is not None:
            champ = self._slots.get(champ_name)
            if champ is not None and champ.shadow is None:
                champ.shadow = serve_drift.ShadowScorer(
                    slot.name, champ.name, slot, self._clock)
        for s in self._slots.values():
            if getattr(s.spec, "shadow_of", None) == slot.name \
                    and slot.shadow is None:
                slot.shadow = serve_drift.ShadowScorer(
                    s.name, slot.name, s, self._clock)

    def _observer_fn(self, slot):
        """dispatch_batch's post-result observer (ISSUE 19): fold the
        scored batch into the slot's drift window and hand (rows,
        scores) to an attached challenger's shadow queue. Runs AFTER
        every future in the batch has settled — on the dispatcher
        (batch path) or a handler thread (express lane); both sinks
        take only their own leaf locks, and an alert transition bumps
        the process counter here (a plain int add) while the event
        payload waits in the tracker for a handler-thread flush."""
        def observe(Xb, scores, lats):
            trk = slot.drift
            if trk is not None \
                    and trk.observe(self._clock(), Xb) is not None:
                tele_counters.record_drift_alert()
            scorer = slot.shadow
            if scorer is not None:
                scorer.enqueue(Xb, scores)
        return observe

    def _express_fn(self, slot):
        def dispatch(batch, depth):
            # The express lane reads the slot's model itself (there is
            # no admission step to capture it at). An eviction landing
            # in the tiny window between the caller's residency check
            # and this read surfaces as _EvictedInFlight, which
            # predict_async turns into reload-and-requeue — never a
            # client-visible failure.
            model = slot.model
            if model is None:
                raise _EvictedInFlight(slot.name)
            lats = dispatch_batch(model, batch, depth, slot.stats,
                                  observer=slot.observer)
            trk = slot.slo
            if trk is not None and lats \
                    and trk.record(self._clock(), lats) is not None:
                tele_counters.record_slo_breach()
        return dispatch

    def _slot(self, name) -> FleetSlot:
        with self._cv:
            slot = self._slots.get(name)
            if slot is None:
                raise UnknownModelError(name, self._slots)
            return slot

    def _next_use_locked(self) -> int:
        self._use_seq += 1
        return self._use_seq

    def _ensure_resident(self, slot: FleetSlot) -> None:
        """Make `slot` resident, loading on THIS (caller) thread if it
        was evicted; concurrent callers coalesce on one load. No fleet
        lock is held across the load itself."""
        with self._cv:
            while slot.loading and not self._closed:
                self._cv.wait()
            if self._closed:
                raise ShuttingDown("fleet engine is shut down")
            if slot.model is not None:
                return
            slot.loading = True
            slot.load_error = None
        try:
            model = self._loader(slot.spec)
            # Publish-side guarantee (ServeEngine._build's contract): no
            # live request ever pays a compile — on an already-warm
            # model this is a handful of cached dispatches.
            model.warmup()
        except Exception as e:  # ddtlint: disable=broad-except
            with self._cv:
                slot.loading = False
                slot.load_error = f"{type(e).__name__}: {e}"
                self._cv.notify_all()
            raise ModelUnavailableError(slot.name, slot.load_error) from e
        # Drift/shadow misconfiguration is a CONFIG error, not a load
        # failure: it must surface as the structured 4xx (ValueError
        # family), never the 503 the except-arm above would wrap it in.
        try:
            drift_trk = slot.drift if slot.drift is not None \
                else self._derive_drift(slot.spec, model, slot.name)
            self._check_shadow_compat(slot, model)
        except ValueError as e:
            with self._cv:
                slot.loading = False
                slot.load_error = f"{type(e).__name__}: {e}"
                self._cv.notify_all()
            raise
        with self._cv:
            slot.loading = False
            slot.model = model
            slot.drift = drift_trk
            slot.last_used = self._next_use_locked()
            reloaded = slot.ever_resident
            slot.ever_resident = True
            if reloaded:
                slot.reloads += 1
            victims = self._evict_locked(keep=slot)
            self._cv.notify_all()
        # Telemetry OUTSIDE the lock (the run log's append is file I/O).
        self._flush_events()
        if reloaded:
            tele_counters.record_fleet_reload()
            self._emit_lifecycle("fleet_reload", slot)
        for v in victims:
            tele_counters.record_fleet_eviction()
            self._emit_lifecycle("fleet_eviction", v)

    def _derive_drift(self, spec, model, name):
        """DriftTracker for a freshly loaded model, honouring the spec's
        tri-state `drift` flag: None = auto (track when the artifact
        carries a training reference histogram), False = never, True =
        require — a reference-less artifact is then a FleetConfigError
        (a ValueError: the HTTP boundary renders it as a structured
        4xx, never a bare 500)."""
        want = getattr(spec, "drift", None)
        if want is False:
            return None
        ref = getattr(getattr(model, "mapper", None), "ref_counts", None)
        if ref is None:
            if want is True:
                # Deferred import: control.py imports this module at
                # load; by the time a model loads, control is long
                # importable — no cycle at module-exec time.
                from ddt_tpu.serve.control import FleetConfigError
                raise FleetConfigError(
                    f"model {name!r}: drift=true but artifact "
                    f"{spec.ref!r} carries no training reference "
                    "histogram (mapper.ref_counts) — re-export from a "
                    "training run that captured one, or drop "
                    "drift=true")
            return None
        return serve_drift.DriftTracker(ref)

    def _check_shadow_compat(self, slot: FleetSlot, model) -> None:
        """Champion/challenger agreement, checked at load time on
        whichever side loads second: a challenger scores the champion's
        OWN binned traffic verbatim, so the widths must match and both
        must speak the same output convention (`raw`). Violations are
        FleetConfigError (structured 4xx), raised before publish so the
        broken pairing never serves."""
        with self._cv:
            pairs = []   # (shadow slot, shadow model, champ slot, champ model)
            champ_name = getattr(slot.spec, "shadow_of", None)
            if champ_name is not None:
                champ = self._slots.get(champ_name)
                if champ is not None and champ.model is not None:
                    pairs.append((slot, model, champ, champ.model))
            for s in self._slots.values():
                if getattr(s.spec, "shadow_of", None) == slot.name \
                        and s.model is not None:
                    pairs.append((s, s.model, slot, model))
        for sh, sh_model, champ, champ_model in pairs:
            if sh_model.n_features != champ_model.n_features:
                from ddt_tpu.serve.control import FleetConfigError
                raise FleetConfigError(
                    f"shadow {sh.name!r} expects {sh_model.n_features} "
                    f"features but champion {champ.name!r} serves "
                    f"{champ_model.n_features} — a challenger must "
                    "score the champion's own traffic")
            if bool(getattr(sh.spec, "raw", False)) \
                    != bool(getattr(champ.spec, "raw", False)):
                from ddt_tpu.serve.control import FleetConfigError
                raise FleetConfigError(
                    f"shadow {sh.name!r} and champion {champ.name!r} "
                    "disagree on raw= — margin-vs-probability "
                    "divergence would be meaningless")

    def _evict_locked(self, keep: "FleetSlot | None") -> list:
        """LRU demotion down to `max_resident` (called with the fleet
        Condition held — after a publish, and by the dispatcher each
        cycle so an over-budget fleet SETTLES once queues drain). Only
        IDLE models are candidates — empty queue, not mid-load, not the
        one just published; while everything is busy the fleet
        overshoots its budget temporarily rather than failing live
        traffic."""
        if self.max_resident is None:
            return []
        victims = []
        while True:
            resident = [s for s in self._slots.values()
                        if s.model is not None]
            if len(resident) <= self.max_resident:
                break
            cands = [s for s in resident
                     if s is not keep and not s.loading
                     and not s.batcher.backlog_rows_locked()]
            if not cands:
                break
            victim = min(cands, key=lambda s: s.last_used)
            # Demotion IS a reference drop: the registry artifact is
            # the cold form, and any batch/express dispatch that
            # already read this reference keeps scoring with it.
            victim.model = None
            victim.evictions += 1
            victims.append(victim)
        return victims

    def _emit_lifecycle(self, kind: str, slot: FleetSlot) -> None:
        """Emit one lifecycle fault event NOW (handler threads only —
        callers hold no fleet lock)."""
        if self.run_log is None:
            return
        self.run_log.emit(
            "fault", kind=kind, model_name=slot.name,
            artifact_digest=getattr(slot.model, "artifact_digest", None)
            if slot.model is not None else None,
            evictions=slot.evictions, reloads=slot.reloads)

    def _queue_eviction_events_locked(self, victims) -> None:
        """Record dispatcher-settled evictions: counters move now
        (plain int adds), the run-log events wait for a handler thread
        (_flush_events) — the dispatcher never touches the log file."""
        for v in victims:
            tele_counters.record_fleet_eviction()
            self._pending_events.append(
                ("fleet_eviction", v.name, v.evictions, v.reloads))

    def _flush_events(self) -> None:
        """Drain dispatcher-buffered lifecycle events AND pending SLO
        breaches AND pending drift alerts into the run log (handler
        threads: health, emit_latency, reload, and the request path
        when a tracker has something waiting)."""
        with self._cv:
            pending, self._pending_events[:] = \
                list(self._pending_events), []
            slots = list(self._slots.values())
        breaches = []
        drifts = []
        for s in slots:
            if s.slo is not None and s.slo.has_pending():
                for b in s.slo.take_pending():
                    breaches.append((s, b))
            if s.drift is not None and s.drift.has_pending():
                for d in s.drift.take_pending():
                    drifts.append((s, d))
        if self.run_log is None:
            return
        for kind, name, evictions, reloads in pending:
            self.run_log.emit("fault", kind=kind, model_name=name,
                              artifact_digest=None,
                              evictions=evictions, reloads=reloads)
        for s, b in breaches:
            self.run_log.emit("fault", kind="slo_breach",
                              model_name=s.name, **b)
            # A breach drags the evidence out with it: the slot's trace
            # ring is flushed as a `serve_trace` event so the slow tail
            # is attributable after the fact, not just counted.
            self.flush_traces(reason="slo_breach", only=s.name)
        for s, d in drifts:
            # Latched alert transitions (drift.py buffered the payload
            # on whatever thread observed it) land as first-class
            # `drift` events — `report drift` reads them back.
            self.run_log.emit("drift", model_name=s.name, **d)

    # ------------------------------------------------------------------ #
    # request path
    # ------------------------------------------------------------------ #

    @property
    def default_model(self) -> "str | None":
        """The implicit routing target: the fleet's single model when
        there is exactly one, else None (requests must name one)."""
        with self._cv:
            return self._order[0] if len(self._order) == 1 else None

    def _resolve_name(self, model: "str | None") -> str:
        """Routed name -> fleet member name, applying the single-model
        default (one resolution shared by predict_async and the raw
        wire path's width lookup, so the two cannot disagree)."""
        name = model if model is not None else self.default_model
        if name is None:
            with self._cv:
                known = list(self._slots)
            raise UnknownModelError(
                model if model is not None else "(unrouted)", known)
        return name

    def n_features_for(self, name: "str | None" = None) -> int:
        """Feature width of the routed model (loads it if evicted —
        the raw wire path needs the width before it can decode a body;
        `None` resolves the single-model default like predict_async)."""
        name = self._resolve_name(name)
        slot = self._slot(name)
        self._ensure_resident(slot)
        model = slot.model
        if model is None:
            raise ModelUnavailableError(name, "evicted during lookup")
        return model.n_features

    def predict_async(self, rows, model: "str | None" = None,
                      trace_id: "str | None" = None) -> PendingRequest:
        name = self._resolve_name(model)
        rows = coerce_rows(rows)
        slot = self._slot(name)
        # SLO-breach / drift-alert sweep: the dispatcher can only
        # BUFFER these (no file I/O on that thread), so the next
        # request for the slot carries them to the log. has_pending is
        # an unlocked truthiness read — zero cost on the quiet path.
        if ((slot.slo is not None and slot.slo.has_pending())
                or (slot.drift is not None
                    and slot.drift.has_pending())):
            self._flush_events()
        # Residency + enqueue retry loop: an eviction can land between
        # the load and the enqueue (or mid-express) — each lap reloads
        # and tries again; the bound is defensive, in practice one lap.
        for _ in range(8):
            self._ensure_resident(slot)
            if self.express_lane and rows.shape[0] == 1:
                req = slot.batcher.express(rows, 1, trace_id=trace_id)
                if req is not None:
                    if isinstance(req.exception(), _EvictedInFlight):
                        continue          # raced an eviction: reload
                    with self._cv:
                        slot.last_used = self._next_use_locked()
                    return req
            with self._cv:
                if self._closed:
                    raise ShuttingDown("fleet engine is shut down")
                # A remove_model racing this request deletes the slot
                # AFTER our lookup: enqueueing into the orphaned slot
                # would hang forever (the dispatcher rotates over
                # _order, which no longer lists it) — re-check
                # membership under the same lock the removal holds.
                if self._slots.get(name) is not slot:
                    raise UnknownModelError(name, self._slots)
                # Enqueue ATOMICALLY with the residency check (the
                # Condition's lock is reentrant): eviction requires an
                # empty queue under this same lock, so once enqueued
                # the model cannot be demoted until the queue drains.
                if slot.model is not None:
                    slot.last_used = self._next_use_locked()
                    return slot.batcher.submit(rows, rows.shape[0],
                                               trace_id=trace_id)
        raise ModelUnavailableError(
            name, "could not win the residency race (reload storm?)")

    def predict(self, rows, model: "str | None" = None,
                timeout: "float | None" = 30.0):
        return self.predict_async(rows, model=model).result(timeout)

    # ------------------------------------------------------------------ #
    # dispatcher thread: weighted deficit round robin
    # ------------------------------------------------------------------ #

    def _rotation_locked(self, start: int) -> list:
        """Slots in DRR rotation order beginning at index `start` (the
        loop passes its own rotation pointer — every `self._rr` access
        stays inside the two Condition-guarded methods that own it)."""
        order = self._order
        if not order:
            return []
        i = start % len(order)
        return [self._slots[n] for n in order[i:] + order[:i]]

    def _backlog_locked(self) -> int:
        return sum(s.batcher.backlog_rows_locked()
                   for s in self._slots.values())

    def _loop(self) -> None:
        while True:
            admitted = []       # (slot, model, batch, depth)
            with self._cv:
                while True:
                    if self._closed and not self._backlog_locked():
                        return
                    now = self._clock()
                    ready = [s for s in self._rotation_locked(self._rr)
                             if (s.batcher.ready_locked(now)
                                 or (self._closed and s.batcher
                                     .backlog_rows_locked()))]
                    if ready:
                        break
                    timeout = None
                    for s in self._slots.values():
                        dl = s.batcher.head_deadline_locked()
                        if dl is not None:
                            t = max(0.0, dl - now)
                            timeout = t if timeout is None \
                                else min(timeout, t)
                    # cv.wait(timeout) parks the thread — no
                    # sleep-polling (the serve-blocking-io contract).
                    self._cv.wait(timeout)
                for slot in ready:
                    # DRR: earn weight x max_batch rows of credit
                    # (capped — credit never banks across idle spells),
                    # then admit micro-batches until it runs out. The
                    # model reference is captured HERE, under the lock
                    # that eviction runs under: every admitted batch is
                    # scored by exactly the version it was admitted
                    # against (old-or-new-never-a-mix, per model).
                    quantum = slot.weight * slot.batcher.max_batch
                    slot.deficit = min(slot.deficit + quantum, quantum)
                    while (slot.deficit > 0
                           and (slot.batcher.ready_locked(self._clock())
                                or self._closed)):
                        batch, depth = slot.batcher.admit_locked()
                        if not batch:
                            break
                        slot.deficit -= sum(r.n for r in batch)
                        admitted.append(
                            (slot, slot.model, batch, depth))
                    if not slot.batcher.backlog_rows_locked():
                        slot.deficit = 0.0
                if self._order:
                    self._rr = (self._rr + 1) % len(self._order)
                # Over-budget settlement: a storm can make EVERY model
                # busy at publish time (eviction skips busy slots), so
                # the fleet overshoots max_resident; the dispatcher
                # settles it back as soon as queues drain — a pure
                # reference drop, nothing blocking (events are buffered
                # for the next handler thread to flush).
                self._queue_eviction_events_locked(
                    self._evict_locked(keep=None))
            for slot, model, batch, depth in admitted:
                if self._on_dispatch is not None:
                    self._on_dispatch(slot.name,
                                      sum(r.n for r in batch))
                if model is None:
                    # Defensive: enqueue-under-lock makes this
                    # unreachable (eviction needs an empty queue), but
                    # a hung waiter would be strictly worse than a loud
                    # per-request error if the invariant ever breaks.
                    for req in batch:
                        req.set_error(ModelUnavailableError(
                            slot.name, "evicted with queued work"))
                    continue
                slot.batcher.dispatch_under_gate(
                    self._batch_fn(model, slot), batch, depth)

    def _batch_fn(self, model, slot):
        def dispatch(batch, depth):
            lats = dispatch_batch(model, batch, depth, slot.stats,
                                  observer=slot.observer)
            trk = slot.slo
            if trk is not None and lats \
                    and trk.record(self._clock(), lats) is not None:
                # Counter now (plain int add — dispatcher-safe); the
                # run-log event waits in the tracker's pending buffer
                # for a handler-thread sweep (serve-blocking-io).
                tele_counters.record_slo_breach()
        return dispatch

    # ------------------------------------------------------------------ #
    # control plane (add / remove / retag) — caller threads only
    # ------------------------------------------------------------------ #

    def add_model(self, spec, *, load: bool = True) -> dict:
        """Add a model to the fleet without restart. Loud on duplicate
        names; `load=True` makes it resident now (evicting LRU models
        past the budget), else it stays cold until first request. A
        FAILED load rolls the slot back out — the HTTP add path has no
        boot-time ref resolution, and a half-added broken member would
        both 503 every routed request and block the corrected retry
        with 'already in the fleet'."""
        with self._cv:
            if self._closed:
                raise ShuttingDown("fleet engine is shut down")
            if spec.name in self._slots:
                raise ValueError(
                    f"model {spec.name!r} is already in the fleet "
                    "(remove it first, or retag it)")
            # Live shadow attach (boot-time specs go through
            # control.validate_specs; this is the POST /models path, so
            # the same topology rules apply here — ValueError lands in
            # the HTTP layer's structured 400 arm).
            champ_name = getattr(spec, "shadow_of", None)
            if champ_name is not None:
                champ = self._slots.get(champ_name)
                if champ is None:
                    raise ValueError(
                        f"shadow_of={champ_name!r} names no fleet "
                        f"member (serving: "
                        f"{', '.join(sorted(self._slots)) or 'none'})")
                if getattr(champ.spec, "shadow_of", None) is not None:
                    raise ValueError(
                        f"model {champ_name!r} is itself a shadow — "
                        "shadow chains are not supported")
                if champ.shadow is not None:
                    raise ValueError(
                        f"model {champ_name!r} already has shadow "
                        f"{champ.shadow.name!r} (one challenger per "
                        "champion; remove it first)")
            slot = self._make_slot_locked(spec)
            self._cv.notify_all()
        if load:
            try:
                self._ensure_resident(slot)
            except BaseException:
                scorers = []
                with self._cv:
                    if self._slots.get(spec.name) is slot:
                        del self._slots[spec.name]
                        self._order.remove(spec.name)
                        self._rr = 0
                        # Detach any scorer the slot creation wired up
                        # (in either direction) — a rolled-back member
                        # must not leave a live challenger thread.
                        for s in self._slots.values():
                            if s.shadow is not None \
                                    and s.shadow.name == spec.name:
                                scorers.append(s.shadow)
                                s.shadow = None
                        if slot.shadow is not None:
                            scorers.append(slot.shadow)
                            slot.shadow = None
                        slot.batcher.fail_pending_locked(
                            UnknownModelError(spec.name, self._slots))
                        self._cv.notify_all()
                for scorer in scorers:
                    scorer.close()    # join: outside the fleet lock
                raise
        return {"name": slot.name, "resident": slot.model is not None,
                "weight": slot.weight}

    def remove_model(self, name) -> dict:
        """Remove a model: queued requests fail loudly (UnknownModel),
        in-flight batches finish with the reference they hold."""
        with self._cv:
            slot = self._slots.get(name)
            if slot is None:
                raise UnknownModelError(name, self._slots)
            if slot.shadow is not None:
                # A shadowed champion stays put until the experiment is
                # torn down explicitly — silently dropping the target
                # of a live comparison would leave the challenger
                # scoring nothing without anyone deciding that.
                raise ValueError(
                    f"model {name!r} is shadowed by "
                    f"{slot.shadow.name!r}; remove the shadow first")
            scorer = None
            champ_name = getattr(slot.spec, "shadow_of", None)
            if champ_name is not None:
                champ = self._slots.get(champ_name)
                if champ is not None and champ.shadow is not None \
                        and champ.shadow.name == name:
                    scorer, champ.shadow = champ.shadow, None
            failed = slot.batcher.fail_pending_locked(
                UnknownModelError(name, set(self._slots) - {name}))
            del self._slots[name]
            self._order.remove(name)
            self._rr = 0
            slot.model = None
            self._cv.notify_all()
        if scorer is not None:
            scorer.close()    # removing the challenger detaches it
        if self.run_log is not None:
            self.run_log.emit("fault", kind="fleet_remove",
                              model_name=name, failed_requests=failed)
        return {"name": name, "failed_requests": failed}

    def spec_for(self, name):
        """The current spec of fleet member `name` (the HTTP control
        plane's retag path derives the replacement spec from it)."""
        return self._slot(name).spec

    def retag(self, name, spec) -> dict:
        """Re-point an existing fleet member at a new reference and hot
        swap it in — the per-model zero-downtime swap (the model
        reference for each batch is read at admission, so requests see
        exactly the old or the new version, never a mix)."""
        slot = self._slot(name)
        new = self._loader(spec)
        new.warmup()
        # Retag re-derives the drift tracker from the NEW artifact: the
        # reference histogram belongs to the training run behind the
        # new model, so the old rolling window is meaningless against
        # it. Misconfig raises (structured 4xx) before any swap.
        new_drift = self._derive_drift(spec, new, name)
        self._check_shadow_compat(slot, new)
        with self._cv:
            if name not in self._slots:
                raise UnknownModelError(name, self._slots)
            old = slot.model
            slot.spec = spec
            # Retag re-derives the SLO tracker from the NEW spec: a
            # changed objective starts a fresh burn history (old-burn
            # vs new-objective comparisons are meaningless).
            objective = getattr(spec, "slo_p99_ms", None)
            slot.slo = SloBurnTracker(objective) if objective else None
            slot.drift = new_drift
            slot.model = new
            slot.ever_resident = True
            slot.last_used = self._next_use_locked()
            victims = self._evict_locked(keep=slot)
            self._cv.notify_all()
        tele_counters.record_serve_hot_swap()
        old_token = old.token if old is not None else None
        if self.run_log is not None:
            self.run_log.emit(
                "fault", kind="hot_swap", model_name=name,
                old=old_token, new=new.token,
                old_artifact=getattr(old, "artifact_digest", None),
                new_artifact=new.artifact_digest)
        for v in victims:
            tele_counters.record_fleet_eviction()
            self._emit_lifecycle("fleet_eviction", v)
        return {"name": name, "old": old_token, "new": new.token,
                "ref": spec.ref}

    # ------------------------------------------------------------------ #
    # telemetry
    # ------------------------------------------------------------------ #

    def _slot_health_locked(self, slot: FleetSlot) -> dict:
        model = slot.model
        out = {
            "resident": model is not None,
            "weight": slot.weight,
            "max_batch": slot.batcher.max_batch,
            "ref": slot.spec.ref,
            "tier": slot.spec.tier,
            "evictions": slot.evictions,
            "reloads": slot.reloads,
            "queued_rows": slot.batcher.backlog_rows_locked(),
            "load_error": slot.load_error,
        }
        if model is not None:
            out.update(model_token=model.token,
                       predict_impl=model.predict_impl,
                       artifact_digest=model.artifact_digest,
                       n_features=model.n_features)
        if slot.slo is not None:
            # Schema-additive (ISSUE 17): SLO fields appear ONLY when
            # the spec declares an objective — a pre-SLO fleet's health
            # payload is byte-identical to before.
            out.update(slo_p99_ms=slot.slo.objective_ms,
                       slo_burn_rate=slot.slo.burn_rates(self._clock()),
                       slo_breaches=slot.slo.breaches)
        if slot.drift is not None:
            # Schema-additive (ISSUE 19): drift fields appear ONLY when
            # the artifact carried a reference histogram (same
            # omit-don't-lie convention as the SLO block). Lock nesting
            # is fleet-Condition -> tracker-leaf-lock, the SloBurnTracker
            # precedent.
            d = slot.drift.state(self._clock())
            out.update(drift_psi_max=d["psi_max"],
                       drift_js_max=d["js_max"],
                       drift_alerting=d["alerting"],
                       drift_alerts=d["alerts"],
                       drift_window_rows=d["window_rows"])
        champ_name = getattr(slot.spec, "shadow_of", None)
        if champ_name is not None:
            out["shadow_of"] = champ_name
        if slot.shadow is not None:
            out["shadow"] = slot.shadow.summary()
        return out

    def health(self) -> dict:
        self._flush_events()
        with self._cv:
            models = {name: self._slot_health_locked(s)
                      for name, s in sorted(self._slots.items())}
            resident = sum(1 for s in self._slots.values()
                           if s.model is not None)
        return {
            "ok": True,
            "fleet": True,
            "models": models,
            "resident": resident,
            "resident_models": resident,
            "backlog_rows": sum(m["queued_rows"]
                                for m in models.values()),
            "max_resident": self.max_resident,
            "express_lane": self.express_lane,
            "evictions": sum(m["evictions"] for m in models.values()),
            "reloads": sum(m["reloads"] for m in models.values()),
        }

    def models(self) -> dict:
        """GET /models payload (the health table, without the envelope)."""
        return self.health()["models"]

    def metrics_snapshot(self) -> dict:
        """Live per-model exposition state for `GET /metrics` — strictly
        read-only (non-resetting histograms, live backlog, SLO burn);
        serve/metrics.py renders it to Prometheus text."""
        now = self._clock()
        with self._cv:
            slots = list(self._slots.values())
            resident = sum(1 for s in slots if s.model is not None)
            backlog = {s.name: s.batcher.backlog_rows_locked()
                       for s in slots}
        models = {}
        for s in slots:
            slo = None
            if s.slo is not None:
                slo = {"objective_ms": s.slo.objective_ms,
                       "burn_rates": s.slo.burn_rates(now),
                       "breaches": s.slo.breaches}
            drift = s.drift.state(now) if s.drift is not None else None
            shadow = s.shadow.summary() if s.shadow is not None else None
            models[s.name] = {"hist": s.stats.metrics_state(),
                              "backlog_rows": backlog[s.name],
                              "slo": slo,
                              "drift": drift,
                              "shadow": shadow}
        return {"models": models, "resident_models": resident,
                "max_resident": self.max_resident}

    def debug_drift(self) -> dict:
        """GET /debug/drift payload: per-model reference/window state,
        worst-first per-feature divergence attribution, and the shadow
        comparison. Handler threads only (flushes pending drift
        events on the way)."""
        self._flush_events()
        now = self._clock()
        with self._cv:
            slots = list(self._slots.values())
        models = {}
        for s in slots:
            rec = {"reference": s.drift is not None,
                   "shadow_of": getattr(s.spec, "shadow_of", None)}
            if s.drift is not None:
                rec["state"] = s.drift.state(now)
                rec["per_feature"] = s.drift.per_feature(now)
            if s.shadow is not None:
                rec["shadow"] = s.shadow.summary()
            models[s.name] = rec
        return {"fleet": True, "models": models}

    def debug_traces(self) -> dict:
        """{model_name: [trace records]} — each slot's ring of the last
        N completed request traces (GET /debug/requests)."""
        with self._cv:
            slots = list(self._slots.values())
        return {s.name: s.stats.traces_snapshot() for s in slots}

    def flush_traces(self, reason: str = "on_demand",
                     only: "str | None" = None) -> int:
        """Flush trace rings into the run log as `serve_trace` events
        (one per model with traces); returns the trace count flushed.
        Handler threads only — this is file I/O."""
        if self.run_log is None:
            return 0
        with self._cv:
            slots = [s for s in self._slots.values()
                     if only is None or s.name == only]
        total = 0
        for slot in slots:
            traces = slot.stats.traces_snapshot()
            if not traces:
                continue
            model = slot.model
            self.run_log.emit(
                "serve_trace", traces=traces, count=len(traces),
                model_name=slot.name,
                model_token=model.token if model is not None else None,
                reason=reason)
            total += len(traces)
        return total

    def window_summaries(self, reset: bool = False) -> dict:
        """{model_name: current-window latency summary} for /stats."""
        with self._cv:
            slots = list(self._slots.values())
        out = {}
        for slot in slots:
            s = slot.stats.window_summary(reset=reset)
            if s["requests"] == 0 and not reset:
                continue
            s["model_name"] = slot.name
            out[slot.name] = s
        return out

    def emit_latency(self, reset: bool = True,
                     only: "str | None" = None) -> dict:
        """Emit one `serve_latency` event PER MODEL with traffic this
        window (the model_name dimension — schema-additive); returns
        {model_name: payload} for the models that emitted. `only`
        restricts emission (and the window reset) to ONE model — the
        per-model `/models/<name>/stats?emit=1` surface must not
        silently discard every OTHER model's window."""
        self._flush_events()
        with self._cv:
            slots = list(self._slots.values())
        out = {}
        for slot in slots:
            if only is not None and slot.name != only:
                continue
            summary = slot.stats.window_summary(reset=reset)
            if summary["requests"] == 0:
                continue
            summary["model_name"] = slot.name
            if slot.slo is not None:
                # The window rides its objective out (schema-additive):
                # `report slo` reads it off old logs without needing
                # the fleet config that set it.
                summary["slo_p99_ms"] = slot.slo.objective_ms
            model = slot.model
            if model is not None:
                summary["model_token"] = model.token
                summary["predict_impl"] = model.predict_impl
                if model.artifact_digest is not None:
                    summary["artifact_digest"] = model.artifact_digest
            if slot.drift is not None:
                # Drift rides the latency window out (schema-additive,
                # ISSUE 19): `report drift` reads divergence off old
                # logs even when no alert ever latched.
                d = slot.drift.state(self._clock())
                if d["psi_max"] is not None:
                    summary["drift_psi_max"] = d["psi_max"]
                    summary["drift_js_max"] = d["js_max"]
                    summary["drift_alerting"] = d["alerting"]
            if slot.shadow is not None:
                sh = slot.shadow.summary()
                summary["shadow_model"] = sh["model"]
                summary["shadow_rows"] = sh["rows"]
                if sh["mean_abs_diff"] is not None:
                    summary["shadow_mean_abs_diff"] = \
                        sh["mean_abs_diff"]
                if sh["ms_p50"] is not None:
                    summary["shadow_ms_p50"] = sh["ms_p50"]
                if sh["dropped"]:
                    summary["shadow_dropped"] = sh["dropped"]
            if self.run_log is not None:
                self.run_log.emit("serve_latency", **summary)
            out[slot.name] = summary
        return out

    def close(self) -> None:
        with self._cv:
            self._closed = True
            scorers = [s.shadow for s in self._slots.values()
                       if s.shadow is not None]
            for slot in self._slots.values():
                slot.batcher.close()      # no own thread: marks closed
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(10.0)
        for scorer in scorers:
            scorer.close()    # joins the scorer thread — no lock held
        self.emit_latency(reset=True)
        if self.run_log is not None:
            self.run_log.close()
