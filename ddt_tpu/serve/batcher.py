"""Admission batching: coalesce concurrent small requests into micro-batches.

The serving tier's queueing half (docs/SERVING.md). Single-row requests
each paying a full dispatch would serialise the device behind per-call
latency; instead, submitters enqueue and a single dispatcher thread
admits work in micro-batches:

- a batch CLOSES when either (a) `max_wait_ms` has elapsed since its
  OLDEST admitted request (the latency budget a request can pay waiting
  for company — default ~1 ms; the deadline is PINNED to that oldest
  request when its window opens and never re-armed by later arrivals,
  so a steady trickle cannot stretch a batch past the head request's
  budget — the fake-clock regression test in tests/test_serve.py), or
  (b) the batch reaches `max_batch` rows (the largest pre-traced
  bucket);
- the dispatcher never sleeps: it parks on a Condition and wakes on
  submit, so an idle server burns nothing and a lone request under no
  load waits only the max-wait admission window;
- EXPRESS LANE (ISSUE 12): when the queue is empty AND no batch is
  mid-dispatch, a single-row request skips the admission window
  entirely — `express()` dispatches it synchronously on the CALLER's
  thread against the pre-traced [1, F] bucket, so an idle server's
  single-row latency is dispatch time, not `max_wait_ms` + dispatch.
  Under load the lane closes (queue non-empty, or the dispatch gate
  held) and requests coalesce exactly as before, so the saturated-
  regime tail cannot regress; the gate also means an express dispatch
  and a batch dispatch never overlap on the device;
- requests are never split across batches and never reordered within
  one — each remembers its row span, so the dispatcher's response
  scatter is positional and a request's rows can neither drop nor
  duplicate (tests/test_serve.py drives this with concurrent
  submitters).

HOT-LOOP MODULE (the ddtlint serve-blocking-io rule): no `time.sleep`,
no synchronous file I/O anywhere in here — a blocked dispatcher thread
stalls EVERY in-flight request's latency, not just its own. The
express lane raises the stakes: the SAME dispatch callable now also
runs on HTTP handler threads, so blocking I/O in the dispatch path
taxes the express path's whole point.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
import uuid


class ShuttingDown(RuntimeError):
    """Raised to waiters whose request cannot be served because the
    batcher is closing."""


#: Trace-id mint (ISSUE 17): a random process prefix + a monotonic
#: sequence — unique enough to join client logs against serve_trace
#: records, and O(1) per request (no per-request entropy syscall in the
#: hot path; the tracing-overhead A/B in scripts/serve_smoke.py holds
#: the default-on path to 1.1x of --no-request-traces).
_TRACE_PREFIX = uuid.uuid4().hex[:12]
_TRACE_SEQ = itertools.count(1)


def _gen_trace_id() -> str:
    return f"{_TRACE_PREFIX}-{next(_TRACE_SEQ):08x}"


def trace_breakdown(req: "PendingRequest") -> "dict | None":
    """The ONE shape home for a completed request's timing breakdown
    (response `X-DDT-Timing` header, the per-model trace ring, and the
    flushed `serve_trace` event all render this dict — they cannot
    drift). Segments, all in ms on the batcher's injected clock:

    - handler_ms — accept -> admit: submit()/express() entry to queue
      append (express: to gate acquisition), i.e. handler-side overhead;
    - queue_ms   — admit -> gate: queue + admission-window wait until
      the batch holding this request acquired the dispatch gate
      (~0 on the express lane — that is the lane's point);
    - gate_ms    — gate -> device: batch assembly under the gate
      (width checks, per-request transform, concat);
    - device_ms  — the device call (score_binned);
    - wake_ms    — device done -> result publication;
    - total_ms   — accept -> publication (the client-observed span
      minus transport).

    Returns None for an untraced or still-pending request."""
    m = req.marks
    if m is None or "wake" not in m:
        return None
    acc = m["accept"]
    adm = m.get("admit", acc)
    gate = m.get("gate", adm)
    dev = m.get("device", gate)
    done = m.get("done", dev)
    wake = m["wake"]
    return {
        "handler_ms": round((adm - acc) * 1e3, 3),
        "queue_ms": round((gate - adm) * 1e3, 3),
        "gate_ms": round((dev - gate) * 1e3, 3),
        "device_ms": round((done - dev) * 1e3, 3),
        "wake_ms": round((wake - done) * 1e3, 3),
        "total_ms": round((wake - acc) * 1e3, 3),
    }


class PendingRequest:
    """One submitted request: rows in, scores (or an exception) out.

    `result()` blocks the SUBMITTER only; the dispatcher thread signals
    the event after the scatter. Latency accounting: `t_submit` is
    stamped at enqueue, the engine stamps completion — the span covers
    queue wait + admission window + dispatch, which is what a caller
    experiences. `model_token` is stamped by the dispatcher with the
    content digest of the model that actually scored this request —
    reading the engine's current token around submit/result instead is
    a race against hot swap (a swap landing in between attributes the
    response to the wrong version; scripts/serve_smoke.py catches it).
    `express` marks a request the express lane dispatched synchronously
    (never queued) — the engine's stats read it for the two-regime
    telemetry. `trace_id`/`marks` carry the ISSUE 17 request trace:
    the id round-trips client -> response header, and `marks` (None
    when tracing is off) accumulates clock marks through the batcher's
    injected clock seam — trace_breakdown() renders them."""

    __slots__ = ("rows", "n", "t_submit", "model_token", "express",
                 "trace_id", "marks", "_event", "_result", "_error")

    def __init__(self, rows, n: int):
        self.rows = rows
        self.n = n
        self.t_submit = time.perf_counter()
        self.model_token = None
        self.express = False
        self.trace_id = None
        self.marks = None
        self._event = threading.Event()
        self._result = None
        self._error = None

    def set_result(self, scores) -> None:
        self._result = scores
        self._event.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve request timed out")
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self) -> "BaseException | None":
        """The delivered error without raising it (None while pending or
        on success) — the fleet's express path inspects this to turn an
        evicted-mid-express race into a reload-and-requeue instead of a
        client-visible failure (ddt_tpu/serve/fleet.py)."""
        return self._error


class MicroBatcher:
    """The admission queue + dispatcher thread.

    `dispatch(batch: list[PendingRequest], queue_depth: int)` is called
    on the dispatcher thread with the admitted batch (total rows <=
    max_batch unless a single over-sized request exceeds it alone —
    those dispatch solo) and the queue depth observed at close time
    (the engine's backlog telemetry). The dispatch callable OWNS
    result/error delivery for every request it receives; if it raises,
    the batcher fails the batch's requests with the exception so no
    submitter hangs."""

    def __init__(self, dispatch, max_wait_ms: float = 1.0,
                 max_batch: int = 256, clock=None, cv=None,
                 own_thread: bool = True, request_traces: bool = True):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._dispatch = dispatch
        # Per-request trace accumulation (ISSUE 17): on by default; the
        # CLI's --no-request-traces turns it off (a client-supplied
        # trace id is still echoed — only the timing marks and ring
        # entries are skipped).
        self.request_traces = bool(request_traces)
        self.max_wait_s = max_wait_ms / 1e3
        self.max_batch = int(max_batch)
        # Injectable clock (tests drive the admission-deadline math with
        # a fake clock; production always runs perf_counter). Used for
        # t_submit stamps and deadline arithmetic only — the Condition
        # waits themselves are real time.
        self._clock = clock if clock is not None else time.perf_counter
        self._q: collections.deque[PendingRequest] = collections.deque()
        self._cv = threading.Condition()
        if cv is not None:
            # DRIVEN mode (ddt_tpu/serve/fleet.py): the fleet engine
            # shares ONE Condition across every model's batcher so its
            # single dispatcher thread can park on all queues at once;
            # submit()/express() notify through it and the *_locked
            # driver surface below is called with it held.
            self._cv = cv
        # Held around EVERY dispatch (batch loop and express lane): an
        # express dispatch and a batch dispatch never overlap on the
        # device, and the express lane only opens when nothing is
        # mid-flight (its tail-latency-never-regresses contract).
        self._gate = threading.Lock()
        self._closed = False
        self._thread = None
        if own_thread:
            self._thread = threading.Thread(
                target=self._loop, name="ddt-serve-batcher", daemon=True)
            self._thread.start()

    def submit(self, rows, n: int,
               trace_id: "str | None" = None) -> PendingRequest:
        """Enqueue one request (`rows` is the request's row block, `n`
        its row count). Returns immediately; wait on the PendingRequest.
        `trace_id` is the client-supplied id (X-DDT-Trace-Id) — honored
        verbatim, else one is minted when tracing is on."""
        req = PendingRequest(rows, n)
        t = self._clock()
        req.t_submit = t
        if self.request_traces:
            req.trace_id = trace_id if trace_id else _gen_trace_id()
            # The clock rides along so the dispatch body (engine.py's
            # dispatch_batch) stamps gate/device/wake marks on the SAME
            # timebase — the clock= seam is the whole breakdown's clock.
            req.marks = {"_clock": self._clock, "accept": t}
        elif trace_id is not None:
            req.trace_id = trace_id
        with self._cv:
            if self._closed:
                raise ShuttingDown("serve batcher is shut down")
            self._q.append(req)
            if req.marks is not None:
                req.marks["admit"] = self._clock()
            self._cv.notify_all()
        return req

    def express(self, rows, n: int,
                trace_id: "str | None" = None) -> "PendingRequest | None":
        """Express lane: dispatch ONE request synchronously on the
        calling thread, bypassing the admission window — but only when
        the lane is open (queue empty, dispatch gate free). Returns the
        completed PendingRequest, or None when the lane is closed and
        the caller should `submit()` into the queue like everyone else.

        Fairness: the lane is only entered from an EMPTY queue, so no
        queued request is ever overtaken; a batch admitted while the
        express dispatch runs blocks on the gate for at most one
        single-row pre-traced dispatch — and under load the queue is
        never empty, so the lane stays shut and the coalesced path is
        untouched (the two-regime contract bench_predict_lut4_ab
        measures)."""
        with self._cv:
            if self._closed:
                raise ShuttingDown("serve batcher is shut down")
            if self._q:
                return None                  # load: coalesce as before
            if not self._gate.acquire(blocking=False):
                return None                  # a dispatch is in flight
        # The try/finally opens IMMEDIATELY on the held path: any raise
        # between a successful acquire and the release (even from
        # PendingRequest construction) would otherwise leak the gate and
        # close the lane — and stall the dispatcher loop — forever (the
        # ddtlint lock-release rule pins this shape).
        try:
            req = PendingRequest(rows, n)
            t = self._clock()
            req.t_submit = t
            req.express = True
            if self.request_traces:
                req.trace_id = trace_id if trace_id else _gen_trace_id()
                # "admit" on the express lane is gate acquisition — the
                # queue was skipped, so queue_ms in the breakdown is the
                # lane's ~0 signature.
                req.marks = {"_clock": self._clock, "accept": t,
                             "admit": t}
            elif trace_id is not None:
                req.trace_id = trace_id
            try:
                self._dispatch([req], 0)
            # Same error contract as the dispatcher loop: a scoring
            # failure reaches THIS request's waiter, never the caller's
            # stack mid-flight.
            except Exception as e:  # ddtlint: disable=broad-except
                if not req.done():
                    req.set_error(e)
        finally:
            self._gate.release()
        return req

    def backlog_rows(self) -> int:
        """Live queued-row count (the /metrics and /healthz live-backlog
        gauge — ISSUE 17): takes the Condition briefly, reads, releases.
        Strictly read-only; never signals the dispatcher."""
        with self._cv:
            return self.backlog_rows_locked()

    def close(self, timeout: float = 5.0) -> None:
        """Stop admitting, drain what is queued, join the dispatcher
        (driven batchers have no thread of their own — the fleet loop
        observes `_closed` and drains)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------ #
    # fleet-driver surface (ddt_tpu/serve/fleet.py)
    # ------------------------------------------------------------------ #
    # The *_locked methods are called by the fleet's single dispatcher
    # thread WITH the shared Condition held (the cv= injected at
    # construction); they never take locks themselves.

    def backlog_rows_locked(self) -> int:
        return sum(r.n for r in self._q)

    def head_deadline_locked(self) -> "float | None":
        """Admission deadline of the OLDEST queued request (the same
        pinned-to-the-head-never-re-armed deadline `_loop` uses), or
        None on an empty queue."""
        if not self._q:
            return None
        return self._q[0].t_submit + self.max_wait_s

    def ready_locked(self, now: float) -> bool:
        """True when a batch should close NOW: the head request's
        window expired, or the row budget is already full."""
        if not self._q:
            return False
        if self._q[0].t_submit + self.max_wait_s <= now:
            return True
        return self.backlog_rows_locked() >= self.max_batch

    def admit_locked(self) -> "tuple[list[PendingRequest], int]":
        """Pop the next micro-batch for the external driver (same FIFO
        never-split-never-reordered admission as the owned loop)."""
        return self._admit_locked()

    def fail_pending_locked(self, err: BaseException) -> int:
        """Fail every queued request with `err` (the fleet control
        plane's remove path); returns how many waiters were failed."""
        n = 0
        while self._q:
            self._q.popleft().set_error(err)
            n += 1
        return n

    def dispatch_under_gate(self, fn, batch, depth: int) -> None:
        """Run one admitted batch through `fn(batch, depth)` under the
        dispatch gate — the fleet driver's batch seam. Same contracts
        as `_loop`: the gate means this never overlaps an express
        dispatch on the same model, and a raising `fn` fails the
        batch's waiters instead of killing the driver thread."""
        try:
            with self._gate:
                fn(batch, depth)
        except Exception as e:  # ddtlint: disable=broad-except
            for req in batch:
                if not req.done():
                    req.set_error(e)

    # ------------------------------------------------------------------ #
    # dispatcher thread
    # ------------------------------------------------------------------ #

    def _admit_locked(self) -> "tuple[list[PendingRequest], int]":
        """Pop the next micro-batch (called with the lock held, queue
        non-empty). Requests are admitted FIFO until the row budget is
        hit; an over-budget FIRST request dispatches alone (large
        requests degrade to solo batches rather than erroring)."""
        batch: list[PendingRequest] = []
        rows = 0
        while self._q:
            nxt = self._q[0]
            if batch and rows + nxt.n > self.max_batch:
                break
            batch.append(self._q.popleft())
            rows += nxt.n
            if rows >= self.max_batch:
                break
        return batch, len(self._q)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q:
                    return                       # closed and drained
                # Admission window: wait for company until the OLDEST
                # queued request's budget expires or the row budget
                # fills. The deadline is computed ONCE from that head
                # request and never touched inside the wake loop — a
                # steady trickle of arrivals re-notifies the Condition
                # but cannot re-arm the window past the head's budget
                # (the fake-clock regression test pins this).
                # cv.wait(timeout) parks the thread — no sleep-polling
                # (the serve-blocking-io contract).
                deadline = self._q[0].t_submit + self.max_wait_s
                while (not self._closed
                       and sum(r.n for r in self._q) < self.max_batch):
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                    if not self._q:              # spurious wake post-drain
                        break
                if not self._q:
                    continue
                batch, depth = self._admit_locked()
            try:
                with self._gate:
                    self._dispatch(batch, depth)
            # The dispatcher thread must survive any scoring failure:
            # deliver it to the batch's waiters and keep serving — dying
            # here would hang every future submitter.
            except Exception as e:  # ddtlint: disable=broad-except
                for req in batch:
                    if not req.done():
                        req.set_error(e)
