"""Admission batching: coalesce concurrent small requests into micro-batches.

The serving tier's queueing half (docs/SERVING.md). Single-row requests
each paying a full dispatch would serialise the device behind per-call
latency; instead, submitters enqueue and a single dispatcher thread
admits work in micro-batches:

- a batch CLOSES when either (a) `max_wait_ms` has elapsed since its
  OLDEST admitted request (the latency budget a request can pay waiting
  for company — default ~1 ms), or (b) the batch reaches `max_batch`
  rows (the largest pre-traced bucket);
- the dispatcher never sleeps: it parks on a Condition and wakes on
  submit, so an idle server burns nothing and a lone request under no
  load waits only the max-wait admission window;
- requests are never split across batches and never reordered within
  one — each remembers its row span, so the dispatcher's response
  scatter is positional and a request's rows can neither drop nor
  duplicate (tests/test_serve.py drives this with concurrent
  submitters).

HOT-LOOP MODULE (the ddtlint serve-blocking-io rule): no `time.sleep`,
no synchronous file I/O anywhere in here — a blocked dispatcher thread
stalls EVERY in-flight request's latency, not just its own.
"""

from __future__ import annotations

import collections
import threading
import time


class ShuttingDown(RuntimeError):
    """Raised to waiters whose request cannot be served because the
    batcher is closing."""


class PendingRequest:
    """One submitted request: rows in, scores (or an exception) out.

    `result()` blocks the SUBMITTER only; the dispatcher thread signals
    the event after the scatter. Latency accounting: `t_submit` is
    stamped at enqueue, the engine stamps completion — the span covers
    queue wait + admission window + dispatch, which is what a caller
    experiences. `model_token` is stamped by the dispatcher with the
    content digest of the model that actually scored this request —
    reading the engine's current token around submit/result instead is
    a race against hot swap (a swap landing in between attributes the
    response to the wrong version; scripts/serve_smoke.py catches it)."""

    __slots__ = ("rows", "n", "t_submit", "model_token", "_event",
                 "_result", "_error")

    def __init__(self, rows, n: int):
        self.rows = rows
        self.n = n
        self.t_submit = time.perf_counter()
        self.model_token = None
        self._event = threading.Event()
        self._result = None
        self._error = None

    def set_result(self, scores) -> None:
        self._result = scores
        self._event.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve request timed out")
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatcher:
    """The admission queue + dispatcher thread.

    `dispatch(batch: list[PendingRequest], queue_depth: int)` is called
    on the dispatcher thread with the admitted batch (total rows <=
    max_batch unless a single over-sized request exceeds it alone —
    those dispatch solo) and the queue depth observed at close time
    (the engine's backlog telemetry). The dispatch callable OWNS
    result/error delivery for every request it receives; if it raises,
    the batcher fails the batch's requests with the exception so no
    submitter hangs."""

    def __init__(self, dispatch, max_wait_ms: float = 1.0,
                 max_batch: int = 256):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._dispatch = dispatch
        self.max_wait_s = max_wait_ms / 1e3
        self.max_batch = int(max_batch)
        self._q: collections.deque[PendingRequest] = collections.deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="ddt-serve-batcher", daemon=True)
        self._thread.start()

    def submit(self, rows, n: int) -> PendingRequest:
        """Enqueue one request (`rows` is the request's row block, `n`
        its row count). Returns immediately; wait on the PendingRequest."""
        req = PendingRequest(rows, n)
        with self._cv:
            if self._closed:
                raise ShuttingDown("serve batcher is shut down")
            self._q.append(req)
            self._cv.notify_all()
        return req

    def close(self, timeout: float = 5.0) -> None:
        """Stop admitting, drain what is queued, join the dispatcher."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout)

    # ------------------------------------------------------------------ #
    # dispatcher thread
    # ------------------------------------------------------------------ #

    def _admit_locked(self) -> "tuple[list[PendingRequest], int]":
        """Pop the next micro-batch (called with the lock held, queue
        non-empty). Requests are admitted FIFO until the row budget is
        hit; an over-budget FIRST request dispatches alone (large
        requests degrade to solo batches rather than erroring)."""
        batch: list[PendingRequest] = []
        rows = 0
        while self._q:
            nxt = self._q[0]
            if batch and rows + nxt.n > self.max_batch:
                break
            batch.append(self._q.popleft())
            rows += nxt.n
            if rows >= self.max_batch:
                break
        return batch, len(self._q)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q:
                    return                       # closed and drained
                # Admission window: wait for company until the OLDEST
                # queued request's budget expires or the row budget
                # fills. cv.wait(timeout) parks the thread — no
                # sleep-polling (the serve-blocking-io contract).
                deadline = self._q[0].t_submit + self.max_wait_s
                while (not self._closed
                       and sum(r.n for r in self._q) < self.max_batch):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                    if not self._q:              # spurious wake post-drain
                        break
                if not self._q:
                    continue
                batch, depth = self._admit_locked()
            try:
                self._dispatch(batch, depth)
            # The dispatcher thread must survive any scoring failure:
            # deliver it to the batch's waiters and keep serving — dying
            # here would hang every future submitter.
            except Exception as e:  # ddtlint: disable=broad-except
                for req in batch:
                    if not req.done():
                        req.set_error(e)
