"""Prometheus-style text exposition for `GET /metrics` (ISSUE 17).

A pure renderer: `render_metrics(counters, snapshot)` turns a process
counter dict (ddt_tpu/telemetry/counters.py `snapshot()`) and an
engine's `metrics_snapshot()` into the text exposition format
(version 0.0.4). STRICTLY READ-ONLY semantics — unlike `/stats?emit=1`,
a scrape never resets a window, never emits an event, never mutates a
counter: the histograms here are the engines' cumulative-since-boot
series (ServeStats._hist on the fixed HIST_BUCKETS_MS ladder), so two
scrapers and an emit loop can interleave freely and every one of them
sees the same monotone streams.

Series emitted:

- ``ddt_<counter>_total``            one gauge/counter per process
  counter (every key of telemetry.counters.snapshot(); counters are
  cumulative since process start);
- ``ddt_serve_latency_ms_bucket{model,le}`` / ``_sum`` / ``_count``
  per-model cumulative histogram — per-bucket counts are converted to
  Prometheus cumulative le-semantics here, with the trailing
  ``le="+Inf"`` bucket equal to ``_count``;
- ``ddt_serve_backlog_rows{model}``  live queued rows (instant gauge);
- ``ddt_serve_resident_models`` / ``ddt_serve_max_resident_models``
  fleet residency (max omitted when unbounded);
- ``ddt_serve_slo_objective_ms{model}`` /
  ``ddt_serve_slo_burn_rate{model,window}`` /
  ``ddt_serve_slo_breaches_total{model}``  only for models with an SLO
  configured (burn-rate windows with too few samples are omitted, not
  rendered as 0 — a 0 burn is a claim, not an absence);
- ``ddt_drift_psi_max{model}`` / ``ddt_drift_js_max{model}`` /
  ``ddt_drift_window_rows{model}`` / ``ddt_drift_alerting{model}`` /
  ``ddt_drift_psi_threshold{model}`` /
  ``ddt_drift_model_alerts_total{model}``  the drift observatory
  (ISSUE 19), only for models whose artifact carried a training
  reference histogram; divergence gauges are omitted (not zeroed)
  below the tracker's min-rows floor. The per-model alert counter is
  named ``_model_alerts_`` so it cannot collide with the process-wide
  ``ddt_drift_alerts_total`` that render_counters already emits;
- ``ddt_shadow_scored_rows_total{model,shadow}`` /
  ``ddt_shadow_mean_abs_diff{model,shadow}`` /
  ``ddt_shadow_dropped_total{model,shadow}``  champion/challenger
  shadow comparison, only on shadowed champions (mean-abs-diff omitted
  until the challenger has actually scored).

No HTTP, no locks, no engine imports — http.py collects the snapshots
(each snapshot method does its own locking) and this module only
formats. Host-side and dependency-free by design.

The exposition primitives (label escaping, sample formatting,
`render_counters`, and the `parse_exposition` test twin) live in
`ddt_tpu/telemetry/exposition.py` since ISSUE 20 — ONE dialect shared
with the training operations plane's statusd `/metrics` — and are
re-exported here so existing importers are untouched. Only the
serve-specific series (latency histograms, backlog, residency, SLO,
drift, shadow) are rendered in this module.
"""

from __future__ import annotations

from ddt_tpu.telemetry.exposition import (_esc, _num, parse_exposition,
                                          render_counters)

__all__ = ["render_counters", "render_metrics", "parse_exposition"]


def _render_hist(model: str, hist: dict) -> "list[str]":
    """Per-bucket counts -> cumulative le-semantics bucket series."""
    out = []
    label = _esc(model)
    cum = 0
    buckets = hist.get("buckets_ms") or []
    counts = hist.get("counts") or []
    for i, le in enumerate(buckets):
        cum += counts[i] if i < len(counts) else 0
        out.append(
            f'ddt_serve_latency_ms_bucket{{model="{label}",'
            f'le="{_num(float(le))}"}} {cum}')
    # The implicit overflow slot: +Inf must equal _count by contract.
    if len(counts) > len(buckets):
        cum += counts[len(buckets)]
    out.append(
        f'ddt_serve_latency_ms_bucket{{model="{label}",le="+Inf"}} {cum}')
    out.append(f'ddt_serve_latency_ms_sum{{model="{label}"}} '
               f'{_num(float(hist.get("sum_ms", 0.0)))}')
    out.append(f'ddt_serve_latency_ms_count{{model="{label}"}} '
               f'{_num(hist.get("count", 0))}')
    return out


def render_metrics(counters: dict, snapshot: dict) -> str:
    """The full `/metrics` body (trailing newline included)."""
    out = render_counters(counters)
    models = snapshot.get("models") or {}
    if models:
        out.append("# TYPE ddt_serve_latency_ms histogram")
        for name in sorted(models):
            out.extend(_render_hist(name, models[name].get("hist") or {}))
        out.append("# TYPE ddt_serve_backlog_rows gauge")
        for name in sorted(models):
            out.append(f'ddt_serve_backlog_rows{{model="{_esc(name)}"}} '
                       f'{_num(models[name].get("backlog_rows", 0))}')
    if snapshot.get("resident_models") is not None:
        out.append("# TYPE ddt_serve_resident_models gauge")
        out.append(f"ddt_serve_resident_models "
                   f"{_num(snapshot['resident_models'])}")
    if snapshot.get("max_resident") is not None:
        out.append("# TYPE ddt_serve_max_resident_models gauge")
        out.append(f"ddt_serve_max_resident_models "
                   f"{_num(snapshot['max_resident'])}")
    slo_models = {n: m["slo"] for n, m in sorted(models.items())
                  if m.get("slo")}
    if slo_models:
        out.append("# TYPE ddt_serve_slo_objective_ms gauge")
        for name, slo in slo_models.items():
            out.append(
                f'ddt_serve_slo_objective_ms{{model="{_esc(name)}"}} '
                f'{_num(float(slo["objective_ms"]))}')
        out.append("# TYPE ddt_serve_slo_burn_rate gauge")
        for name, slo in slo_models.items():
            for window, rate in sorted(
                    (slo.get("burn_rates") or {}).items()):
                if rate is None:
                    continue        # not enough samples: omit, don't lie
                out.append(
                    f'ddt_serve_slo_burn_rate{{model="{_esc(name)}",'
                    f'window="{_esc(window)}"}} {_num(float(rate))}')
        out.append("# TYPE ddt_serve_slo_breaches_total counter")
        for name, slo in slo_models.items():
            out.append(
                f'ddt_serve_slo_breaches_total{{model="{_esc(name)}"}} '
                f'{_num(slo.get("breaches", 0))}')
    drift_models = {n: m["drift"] for n, m in sorted(models.items())
                    if m.get("drift")}
    if drift_models:
        out.append("# TYPE ddt_drift_window_rows gauge")
        for name, d in drift_models.items():
            out.append(
                f'ddt_drift_window_rows{{model="{_esc(name)}"}} '
                f'{_num(d.get("window_rows", 0))}')
        out.append("# TYPE ddt_drift_psi_threshold gauge")
        for name, d in drift_models.items():
            out.append(
                f'ddt_drift_psi_threshold{{model="{_esc(name)}"}} '
                f'{_num(float(d["threshold"]))}')
        # Divergence gauges only once the window clears the tracker's
        # min-rows floor (psi_max is None below it): omit, don't lie.
        scored = {n: d for n, d in drift_models.items()
                  if d.get("psi_max") is not None}
        if scored:
            out.append("# TYPE ddt_drift_psi_max gauge")
            for name, d in scored.items():
                out.append(
                    f'ddt_drift_psi_max{{model="{_esc(name)}"}} '
                    f'{_num(float(d["psi_max"]))}')
            out.append("# TYPE ddt_drift_js_max gauge")
            for name, d in scored.items():
                out.append(
                    f'ddt_drift_js_max{{model="{_esc(name)}"}} '
                    f'{_num(float(d["js_max"]))}')
        out.append("# TYPE ddt_drift_alerting gauge")
        for name, d in drift_models.items():
            out.append(
                f'ddt_drift_alerting{{model="{_esc(name)}"}} '
                f'{_num(bool(d.get("alerting")))}')
        out.append("# TYPE ddt_drift_model_alerts_total counter")
        for name, d in drift_models.items():
            out.append(
                f'ddt_drift_model_alerts_total{{model="{_esc(name)}"}} '
                f'{_num(d.get("alerts", 0))}')
    shadow_models = {n: m["shadow"] for n, m in sorted(models.items())
                     if m.get("shadow")}
    if shadow_models:
        out.append("# TYPE ddt_shadow_scored_rows_total counter")
        for name, sh in shadow_models.items():
            out.append(
                f'ddt_shadow_scored_rows_total{{model="{_esc(name)}",'
                f'shadow="{_esc(sh["model"])}"}} '
                f'{_num(sh.get("rows", 0))}')
        diffed = {n: sh for n, sh in shadow_models.items()
                  if sh.get("mean_abs_diff") is not None}
        if diffed:
            out.append("# TYPE ddt_shadow_mean_abs_diff gauge")
            for name, sh in diffed.items():
                out.append(
                    f'ddt_shadow_mean_abs_diff{{model="{_esc(name)}",'
                    f'shadow="{_esc(sh["model"])}"}} '
                    f'{_num(float(sh["mean_abs_diff"]))}')
        out.append("# TYPE ddt_shadow_dropped_total counter")
        for name, sh in shadow_models.items():
            out.append(
                f'ddt_shadow_dropped_total{{model="{_esc(name)}",'
                f'shadow="{_esc(sh["model"])}"}} '
                f'{_num(sh.get("dropped", 0))}')
    return "\n".join(out) + "\n"
