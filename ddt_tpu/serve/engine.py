"""ServeEngine: device-resident models + micro-batch scoring + SLO stats.

The serving tier's scoring half (docs/SERVING.md). One engine owns:

- a `ServableModel` per live model version — the per-model prologue
  (mapper validation, CompiledEnsemble build, optional int8 LUT
  quantization, device upload, bucket-shape warm-up traces) paid ONCE
  at publish time, so the request path is: bin rows -> pad to bucket ->
  one pre-traced dispatch -> scatter (the api.predict per-call prologue
  hoist, ISSUE 8 satellite);
- a `MicroBatcher` whose dispatcher scores each admitted batch against
  the model reference read ONCE per batch — hot-swap is an atomic
  reference publish, so every request observes exactly the old or the
  new model, never a mix (tests/test_serve.py pins this mid-flight);
- `ServeStats`, the first-class latency telemetry: per-request p50/p99/
  p999, coalesce width, queue depth — emitted as the run log's
  `serve_latency` event (schema v4) and surfaced by `cli report`'s
  serving section, the same observatory that attributes training phases.

HOT-LOOP MODULE (the ddtlint serve-blocking-io rule): no `time.sleep`,
no synchronous file reads — model files are loaded by the CALLER
(cli/http layer) and handed in as ready ModelBundles.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import logging
import threading
import time

import numpy as np

from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.serve.batcher import (MicroBatcher, PendingRequest,
                                   trace_breakdown)
from ddt_tpu.telemetry import counters as tele_counters
# Host-side probability transform (ONE home shared with api.predict —
# no device round-trip for an [R]-sized vector on the request path).
from ddt_tpu.utils.metrics import predict_proba_np as proba_np

log = logging.getLogger("ddt_tpu.serve")


def normalize_quantize(q) -> "str | None":
    """Normalize every spelling of the serving quantization tier to
    None | "int8" | "int4" (the ladder docs/SERVING.md tabulates).
    Accepts the legacy bool opt-in (True = the int8 TreeLUT tier), the
    cfg.predict_impl spellings ("lut"/"lut4"), and the leaf-dtype
    spellings the registry manifests carry."""
    if q is None or q is False:
        return None
    if q is True:
        return "int8"
    s = str(q).lower()
    if s in ("", "none", "false", "f32"):
        return None
    if s in ("int8", "lut", "true", "float16"):
        return "int8"
    if s in ("int4", "lut4"):
        return "int4"
    raise ValueError(
        f"unknown quantization tier {q!r} (expected int8 or int4)")


#: serving tier -> the cfg.predict_impl that dispatches it.
TIER_IMPL = {"int8": "lut", "int4": "lut4"}
#: serving tier -> the QuantizedTables leaf dtype it quantizes to.
TIER_LEAF_DTYPE = {"int8": "float16", "int4": "int4"}


def default_buckets(max_batch: int) -> tuple[int, ...]:
    """Power-of-two pad-to-bucket ladder up to max_batch — the FIXED set
    of batch shapes every dispatch rides (each bucket traces once at
    warm-up; zero retracing under load)."""
    out = [1]
    while out[-1] < max_batch:
        out.append(min(out[-1] * 2, max_batch))
    return tuple(out)


def bucket_for(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class ServableModel:
    """One model version, fully prepared to score micro-batches.

    Build cost (validation + CompiledEnsemble + optional quantized
    tables + device upload + one traced dispatch per bucket) is paid
    here, off the request path; `score()` is transform + pad + dispatch.
    Instances are immutable once built — the engine swaps whole
    references.

    Subclass seam: `_invoke(Xb)` is the one device-dispatch point the
    pad/chunk/probability logic funnels through — the registry's
    AOT-restored model (ddt_tpu/registry/loader.py) overrides ONLY it,
    scoring through deserialized StableHLO instead of the backend's
    traced path, and inherits every shape contract here verbatim."""

    #: short registry digest when this model came from an artifact
    #: (stamped into serve_latency / hot_swap events); None for models
    #: published straight from a file or bundle.
    artifact_digest: "str | None" = None
    #: True when scoring rides deserialized AOT blobs (zero retrace).
    aot: bool = False
    #: RestoredModel pins the tier it restored; backend-scoring models
    #: leave this None and ask the backend what actually resolved.
    _impl_override: "str | None" = None

    def __init__(self, bundle, backend, *, quantize=False,
                 buckets: tuple[int, ...] = (1,), raw: bool = False,
                 tables=None):
        from ddt_tpu.api import validate_mapper_model

        self.ens = bundle.ensemble
        self.mapper = bundle.mapper
        self.backend = backend
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.raw = bool(raw)
        self.quantize_tier = normalize_quantize(quantize)
        self.quantized = self.quantize_tier is not None
        if self.mapper is not None:
            # The full mapper-vs-model contract (missing-bin policy,
            # identity-binned categorical columns), checked ONCE per
            # model version — api.predict pays this per call.
            validate_mapper_model(self.mapper, self.ens)
        self.compiled = self.ens.compile(tree_chunk=64)
        self.token = self.compiled.token
        if self.quantize_tier:
            # Error contract rides on the tables (ops/predict_lut.py);
            # recorded here so /healthz and the smoke test can surface
            # the served bound. Pre-built `tables` (the registry's
            # carried lut_tables.npz, token-pinned by the loader) take
            # precedence over re-quantizing: the exported quantized
            # representation is what serves, even across version skew.
            if tables is not None:
                # Carried tables define the representation; an int4
                # request must get int4 tables (an int8 artifact cannot
                # silently serve as the int4 tier, or the reported
                # error bound would describe the wrong grid).
                if ((tables.leaf_dtype == "int4")
                        != (self.quantize_tier == "int4")):
                    raise ValueError(
                        f"carried tables are leaf_dtype="
                        f"{tables.leaf_dtype!r} but the serving tier is "
                        f"{self.quantize_tier!r}; re-export with "
                        f"--quantize={self.quantize_tier}")
                # Seed the compiled model's memo so the backend's LUT
                # dispatch consumes THESE tables, not a re-derivation —
                # keyed by THEIR leaf_dtype, not the default's.
                self.compiled.seed_quantized(tables)
                self.tables = self.compiled.quantize(
                    leaf_dtype=tables.leaf_dtype)
            else:
                self.tables = self.compiled.quantize(
                    leaf_dtype=TIER_LEAF_DTYPE[self.quantize_tier])
            self.max_abs_err = self.tables.max_abs_err
        else:
            self.tables = None
            self.max_abs_err = 0.0

    @property
    def predict_impl(self) -> str:
        """The tier ACTUALLY serving this model ("lut4" | "lut" |
        "f32") — asks the backend what its fallback ladder resolved, so
        a silent VMEM-guard trip is visible in /healthz and
        serve_latency instead of only in debug logs (resolution happens
        at warmup, before the model is ever published)."""
        if self._impl_override is not None:
            return self._impl_override
        be = self.backend
        if be is not None and hasattr(be, "resolved_predict_impl"):
            return be.resolved_predict_impl(self.token)
        return "f32"

    @property
    def n_features(self) -> int:
        return int(self.ens.n_features)

    def transform(self, rows: np.ndarray) -> np.ndarray:
        """Raw float rows -> uint8 bins with the TRAINING-TIME mapper
        (never refit — the round-1 verdict contract)."""
        if self.mapper is None:
            raise ValueError(
                "model artifact carries no bin mapper; submit pre-binned "
                "uint8 rows")
        return self.mapper.transform(rows)

    def score_binned(self, Xb: np.ndarray) -> np.ndarray:
        """Scores for a BINNED block, padded to the nearest bucket so
        the dispatch rides a pre-traced shape."""
        n = Xb.shape[0]
        cap = self.buckets[-1]
        if n > cap:
            # An over-sized solo request must ALSO ride pre-traced
            # shapes: score it in largest-bucket pieces rather than
            # handing the backend a never-warmed shape (each distinct
            # over-size n would pay a fresh compile on the shared
            # dispatcher thread, stalling every queued request).
            # Probabilities are per-row, so piecewise == whole-batch.
            return np.concatenate([self.score_binned(Xb[i:i + cap])
                                   for i in range(0, n, cap)])
        b = bucket_for(n, self.buckets)
        if n < b:
            Xb = np.concatenate(
                [Xb, np.zeros((b - n, Xb.shape[1]), np.uint8)])
        out = self._invoke(Xb)[:n]
        return out if self.raw else proba_np(out, self.ens.loss)

    def _invoke(self, Xb: np.ndarray) -> np.ndarray:
        """One raw-score dispatch at an exact bucket shape (see the
        class doc's subclass seam)."""
        return self.backend.predict_raw(self.ens, Xb,
                                        compiled=self.compiled)

    def warmup(self) -> None:
        """Trace every bucket shape BEFORE the model is published — a
        swap never makes a live request pay a compile."""
        dummy = np.zeros((1, self.n_features), np.uint8)
        for b in self.buckets:
            self.score_binned(np.repeat(dummy, b, axis=0))


@dataclasses.dataclass
class _Window:
    """One latency-accounting window (reset on each serve_latency emit).

    BOUNDED: a persistent server nobody polls (`cli serve` with no
    /stats?emit=1 caller and no run log) must not accumulate per-request
    floats forever — the sample deques keep the most recent CAP
    requests/batches, so quantiles degrade to trailing-window estimates
    under unpolled steady load instead of the process OOMing. `requests`
    and `batches` stay exact counts regardless."""

    CAP = 65_536

    latencies_ms: collections.deque = dataclasses.field(
        default_factory=lambda: collections.deque(maxlen=_Window.CAP))
    widths: collections.deque = dataclasses.field(
        default_factory=lambda: collections.deque(maxlen=_Window.CAP))
    requests: int = 0
    queue_depth_max: int = 0
    batches: int = 0
    express: int = 0            # requests the express lane dispatched
    t_start: float = dataclasses.field(default_factory=time.perf_counter)


#: FIXED log-spaced latency histogram bucket upper bounds in ms (the
#: /metrics exposition's `le=` ladder, ISSUE 17): 0.1 ms doubling to
#: ~3.3 s, plus an implicit +Inf overflow bucket. Fixed — never derived
#: from observed data — so two scrapes (or two processes) are always
#: bucket-compatible, the property Prometheus histogram aggregation
#: assumes.
HIST_BUCKETS_MS = tuple(round(0.1 * 2.0 ** i, 4) for i in range(16))


def _quantile(sorted_vals: list, q: float) -> float:
    """Nearest-rank quantile on a pre-sorted list (p999 on a 100-request
    smoke run must be the honest max, not an interpolation artifact)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(np.ceil(q * len(sorted_vals))) - 1)
    return float(sorted_vals[max(0, i)])


class ServeStats:
    """Thread-safe latency/coalesce accounting: a bounded all-time ring
    plus the current emit window, a NON-RESETTING log-spaced latency
    histogram (the /metrics exposition — scrapes never reset it, unlike
    the emit window), and a bounded ring of the last TRACE_RING
    completed request traces (GET /debug/requests)."""

    RING = 65_536
    TRACE_RING = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._all = collections.deque(maxlen=self.RING)
        self._win = _Window()
        self.requests = 0
        self.coalesce_max = 0
        self.express = 0
        # Cumulative per-bucket counts on the FIXED HIST_BUCKETS_MS
        # ladder (+1 overflow slot) + the running latency sum — the
        # strictly monotonic state /metrics renders; `requests` above is
        # the matching _count series.
        self._hist = [0] * (len(HIST_BUCKETS_MS) + 1)
        self._hist_sum_ms = 0.0
        self._traces: collections.deque = collections.deque(
            maxlen=self.TRACE_RING)

    def record_batch(self, n_requests: int, queue_depth: int,
                     latencies_ms: list, express: bool = False,
                     traces: "list | None" = None) -> None:
        with self._lock:
            self.requests += n_requests
            self.coalesce_max = max(self.coalesce_max, n_requests)
            self._all.extend(latencies_ms)
            for v in latencies_ms:
                self._hist[bisect.bisect_left(HIST_BUCKETS_MS, v)] += 1
                self._hist_sum_ms += v
            if traces:
                self._traces.extend(traces)
            w = self._win
            w.batches += 1
            w.requests += n_requests
            w.widths.append(n_requests)
            w.queue_depth_max = max(w.queue_depth_max, queue_depth)
            w.latencies_ms.extend(latencies_ms)
            if express:
                self.express += n_requests
                w.express += n_requests

    def _summary_locked(self, w: _Window) -> dict:
        lat = sorted(w.latencies_ms)
        return {
            "requests": w.requests,
            "batches": w.batches,
            "window_s": round(time.perf_counter() - w.t_start, 6),
            "p50_ms": round(_quantile(lat, 0.50), 4),
            "p99_ms": round(_quantile(lat, 0.99), 4),
            "p999_ms": round(_quantile(lat, 0.999), 4),
            "max_ms": round(lat[-1], 4) if lat else 0.0,
            "coalesce_mean": (round(float(np.mean(w.widths)), 3)
                              if w.widths else 0.0),
            "coalesce_max": max(w.widths) if w.widths else 0,
            "queue_depth_max": w.queue_depth_max,
            "express": w.express,
        }

    def window_summary(self, reset: bool = False) -> dict:
        """Current window's latency summary (the serve_latency payload);
        `reset=True` starts a fresh window (emit semantics)."""
        with self._lock:
            out = self._summary_locked(self._win)
            if reset:
                self._win = _Window()
        return out

    def snapshot(self) -> dict:
        """All-time view for /healthz & tests."""
        with self._lock:
            lat = sorted(self._all)
            return {
                "requests": self.requests,
                "coalesce_max": self.coalesce_max,
                "express": self.express,
                "p50_ms": round(_quantile(lat, 0.50), 4),
                "p99_ms": round(_quantile(lat, 0.99), 4),
                "p999_ms": round(_quantile(lat, 0.999), 4),
            }

    def metrics_state(self) -> dict:
        """The non-resetting histogram state the /metrics exposition
        renders: fixed bucket bounds, cumulative-compatible per-bucket
        counts (last slot = +Inf overflow), running sum, and the
        lifetime request count. STRICTLY read-only — a scrape must
        never perturb the emit window (the /metrics vs /stats?emit=1
        contract tests/test_serve.py pins)."""
        with self._lock:
            return {"buckets_ms": list(HIST_BUCKETS_MS),
                    "counts": list(self._hist),
                    "sum_ms": round(self._hist_sum_ms, 4),
                    "count": self.requests,
                    "express": self.express}

    def traces_snapshot(self) -> list:
        """Completed-trace ring, oldest first (GET /debug/requests and
        the serve_trace flush read this; read-only like metrics_state)."""
        with self._lock:
            return list(self._traces)


def coerce_rows(rows) -> np.ndarray:
    """Submit-side row normalization shared by ServeEngine and the
    fleet engine: [F] promotes to [1, F], anything but 2-D is refused,
    and non-uint8 input becomes contiguous f32 (the transform path's
    dtype; uint8 rows are pre-binned and pass through untouched)."""
    rows = np.asarray(rows)
    if rows.ndim == 1:
        rows = rows[None, :]
    if rows.ndim != 2:
        raise ValueError(f"rows must be [n, F], got {rows.shape}")
    if rows.dtype != np.uint8:
        rows = np.ascontiguousarray(rows, np.float32)
    return rows


def dispatch_batch(model, batch, queue_depth: int, stats,
                   observer=None) -> list:
    """Score ONE admitted micro-batch against `model` and deliver every
    result/error — the per-batch body shared by ServeEngine._dispatch
    and the fleet engine's per-model dispatch (ddt_tpu/serve/fleet.py).
    The caller read the model reference ONCE (hot-swap/eviction
    atomicity: every request in the batch is scored by exactly this
    version); this function never touches engine state beyond `stats`.
    Returns the per-request latencies (ms) of the delivered requests —
    the fleet's SLO burn-rate tracker consumes them.

    Raw float requests bin HERE, under the same model that scores them —
    binning at submit time could pair model A's bins with model B's
    trees across a swap. Transform failures are PER-REQUEST: a malformed
    submission fails its own waiter only, never the valid requests that
    happened to share its admission window.

    `observer(Xb, scores, lats)` — the drift/shadow seam (ISSUE 19,
    ddt_tpu/serve/drift.py) — runs AFTER every waiter has its result:
    structurally off the response path, so champion responses are
    bit-identical with or without it, and a failing observer is
    contained (the dispatcher thread must survive anything a tracker
    raises). It sees the batch exactly as scored: the concatenated
    binned uint8 matrix and this model's scores.

    Trace marks (ISSUE 17) ride the requests' own `marks` dicts on the
    batcher's injected clock (marks carry the clock — the whole
    breakdown stays on one timebase): `gate` at entry (the dispatch
    gate is held here), `device`/`done` around the device call, `wake`
    just before result publication. Completed breakdowns land in the
    stats trace ring BEFORE any waiter wakes — a client that queries
    /debug/requests the moment result() returns finds its own trace."""
    clk = None
    for r in batch:
        if r.marks is not None:
            clk = r.marks["_clock"]
            break
    if clk is not None:
        t = clk()
        for r in batch:
            if r.marks is not None:
                r.marks["gate"] = t
    good, blocks = [], []
    for r in batch:
        # Feature-count check against the model ACTUALLY scoring this
        # batch (submit-time validation saw the pre-swap model; a swap
        # to a different-width model must fail only the stale-width
        # requests, never the valid ones sharing their window).
        if r.rows.shape[1] != model.n_features:
            r.set_error(ValueError(
                f"rows have {r.rows.shape[1]} features; the "
                f"serving model expects {model.n_features}"))
            continue
        if r.rows.dtype == np.uint8:
            good.append(r)
            blocks.append(r.rows)
            continue
        try:
            blocks.append(model.transform(r.rows))
            good.append(r)
        # Delivered to this request's own waiter; co-batched requests
        # proceed.
        except Exception as e:  # ddtlint: disable=broad-except
            r.set_error(e)
    if not good:
        return []
    Xb = blocks[0] if len(blocks) == 1 else np.concatenate(blocks)
    if clk is not None:
        t = clk()
        for r in good:
            if r.marks is not None:
                r.marks["device"] = t
    scores = model.score_binned(Xb)
    done = time.perf_counter()
    if clk is not None:
        t = clk()
        for r in good:
            if r.marks is not None:
                r.marks["done"] = t
    lats = [(done - r.t_submit) * 1e3 for r in good]
    express = bool(good and good[0].express)
    traces = None
    if clk is not None:
        t_wake = clk()
        traces = []
        for r in good:
            if r.marks is None:
                continue
            r.marks["wake"] = t_wake
            rec = {"trace_id": r.trace_id, "rows": r.n,
                   "express": bool(r.express)}
            rec.update(trace_breakdown(r))
            traces.append(rec)
    # Stats land BEFORE any waiter wakes: a caller that resets the
    # stats window the moment result() returns must find this batch in
    # the window it completed in, and never see it leak into the next
    # one (bench_serve_latency's per-QPS arms do exactly that).
    tele_counters.record_serve_requests(len(good))
    tele_counters.record_serve_batch()
    if express:
        tele_counters.record_serve_express()
    stats.record_batch(len(good), queue_depth, lats, express=express,
                       traces=traces)
    off = 0
    for req in good:
        # Attribution BEFORE the result event fires: a waiter that
        # wakes on set_result must already see which version scored it
        # (hot-swap attribution — PendingRequest.model_token).
        req.model_token = model.token
        req.set_result(scores[off:off + req.n])
        off += req.n
    if observer is not None:
        try:
            observer(Xb, scores, lats)
        except Exception:  # ddtlint: disable=broad-except
            # Observers (drift accumulation, shadow enqueue) are strictly
            # best-effort: they must never take the dispatch loop down or
            # touch the already-delivered results.
            pass
    return lats


class ServeEngine:
    """The persistent scoring process's core (transport-agnostic: the
    HTTP front end, the CLI, tests, and the bench all drive this same
    object).

    Request path: submit -> admission batch (MicroBatcher) -> one
    dispatch against the model reference read at batch start -> scatter
    -> per-request latency recorded. Model path: `swap(bundle)` builds
    + warms the new ServableModel entirely off the request path, then
    publishes the reference atomically (in-flight batches keep scoring
    the version they started with)."""

    def __init__(self, bundle, cfg: TrainConfig | None = None, *,
                 backend=None, max_wait_ms: float = 1.0,
                 max_batch: int = 256, quantize=False,
                 raw: bool = False, run_log=None,
                 express_lane: bool = True,
                 model_name: "str | None" = None,
                 request_traces: bool = True):
        from ddt_tpu.telemetry.events import RunLog

        self.cfg = cfg if cfg is not None else TrainConfig()
        # Optional fleet-style identity (ISSUE 15): when set, every
        # serve_latency window, hot_swap event, and /healthz payload
        # carries the model_name dimension — schema-additive, absent on
        # anonymous single-model servers so old logs/consumers are
        # untouched.
        self.model_name = model_name
        self.quantize_tier = normalize_quantize(quantize)
        want_impl = TIER_IMPL.get(self.quantize_tier)
        if want_impl is not None and self.cfg.predict_impl != want_impl:
            # quantize= IS the LUT-tier opt-in — the backend dispatch
            # and the engine's health/error-bound reporting must agree.
            self.cfg = self.cfg.replace(predict_impl=want_impl)
        self.backend = backend if backend is not None \
            else get_backend(self.cfg)
        self.buckets = default_buckets(max_batch)
        self.quantize = self.quantize_tier is not None
        self.raw = bool(raw)
        self.express_lane = bool(express_lane)
        self.stats = ServeStats()
        self.run_log = RunLog.coerce(run_log)
        # Registry root for reference-based hot swaps (`cli serve
        # --registry` sets it; the HTTP layer resolves refs — this
        # module never does file I/O, the serve-blocking-io contract).
        self.registry_root: "str | None" = None
        self.request_traces = bool(request_traces)
        self._swap_lock = threading.Lock()
        self._model = self._build(bundle)
        self._batcher = MicroBatcher(self._dispatch,
                                     max_wait_ms=max_wait_ms,
                                     max_batch=max_batch,
                                     request_traces=self.request_traces)

    # ------------------------------------------------------------------ #
    # model lifecycle
    # ------------------------------------------------------------------ #

    def _build(self, bundle) -> ServableModel:
        if isinstance(bundle, ServableModel):
            # A prebuilt model (the registry loader's AOT restore, or a
            # caller-constructed ServableModel): publish as-is — its
            # prologue was paid where it was built. Warm-up is repeated
            # here because it is the PUBLISH-side guarantee that no
            # live request ever pays a compile; on an already-warm
            # model it is a handful of cached dispatches.
            bundle.warmup()
            return bundle
        m = ServableModel(bundle, self.backend,
                          quantize=self.quantize_tier,
                          buckets=self.buckets, raw=self.raw)
        m.warmup()
        return m

    @property
    def model_token(self) -> str:
        return self._model.token

    @property
    def n_features(self) -> int:
        """Feature width of the CURRENTLY served model (the raw wire
        path derives row count from it; a request racing a hot swap is
        re-validated at dispatch like every other)."""
        return self._model.n_features

    def swap(self, bundle) -> dict:
        """Zero-downtime hot swap: build + warm the new version OFF the
        request path, then publish atomically. Returns {old, new} tokens
        (idempotent swaps — same content digest — still republish, which
        is harmless and keeps the semantics trivial)."""
        with self._swap_lock:               # serialize concurrent swaps
            new = self._build(bundle)
            old = self._model.token
            old_digest = self._model.artifact_digest
            # Single-assignment publish: readers (_dispatch, health,
            # the express lane) take ONE unlocked reference read and see
            # exactly the old or the new model, never a mix — the
            # declared exemption the threadmodel pass verifies stays a
            # lone reference store.
            self._model = new  # ddtlint: atomic-publish
        tele_counters.record_serve_hot_swap()
        if self.run_log is not None:
            # Registry provenance rides on the event: which ARTIFACT
            # (not just which content token) is serving before/after —
            # the digest is how an operator joins a swap to `registry
            # list` and to the training run's own log (docs/REGISTRY.md).
            extra = ({"model_name": self.model_name}
                     if self.model_name is not None else {})
            self.run_log.emit("fault", kind="hot_swap", old=old,
                              new=new.token,
                              old_artifact=old_digest,
                              new_artifact=new.artifact_digest,
                              **extra)
        log.info("hot-swapped model %s -> %s", old[:12], new.token[:12])
        return {"old": old, "new": new.token}

    # ------------------------------------------------------------------ #
    # request path
    # ------------------------------------------------------------------ #

    def predict_async(self, rows: np.ndarray,
                      trace_id: "str | None" = None) -> PendingRequest:
        rows = coerce_rows(rows)
        if rows.shape[1] != self._model.n_features:
            raise ValueError(
                f"rows have {rows.shape[1]} features; the served model "
                f"expects {self._model.n_features}")
        if self.express_lane and rows.shape[0] == 1:
            # Express lane (ISSUE 12): with an empty queue and no batch
            # mid-dispatch, a single-row request scores RIGHT HERE on
            # the caller's thread against the pre-traced [1, F] bucket
            # — no admission window, no handoff. Under load express()
            # returns None and the request coalesces like any other
            # (tail latency never regresses; batcher.py documents the
            # fairness argument).
            req = self._batcher.express(rows, 1, trace_id=trace_id)
            if req is not None:
                return req
        return self._batcher.submit(rows, rows.shape[0],
                                    trace_id=trace_id)

    def predict(self, rows: np.ndarray, timeout: float | None = 30.0):
        return self.predict_async(rows).result(timeout)

    def _dispatch(self, batch, queue_depth: int) -> None:
        # ONE model reference per micro-batch: every request in it is
        # scored by exactly this version (hot-swap atomicity); the
        # per-batch body lives in dispatch_batch (shared with the fleet
        # engine's per-model dispatch).
        model = self._model
        dispatch_batch(model, batch, queue_depth, self.stats)

    # ------------------------------------------------------------------ #
    # telemetry
    # ------------------------------------------------------------------ #

    def emit_latency(self, reset: bool = True) -> dict | None:
        """Emit the current window as a `serve_latency` run-log event
        (schema v4); returns the payload (None when the window is empty
        — an idle server emits nothing)."""
        summary = self.stats.window_summary(reset=reset)
        if summary["requests"] == 0:
            return None
        m = self._model
        summary["model_token"] = m.token
        if self.model_name is not None:
            summary["model_name"] = self.model_name
        # The tier ACTUALLY serving (satellite fix, ISSUE 12): a vmem
        # guard that silently degraded lut4 -> lut -> f32 shows up in
        # every telemetry window, not only in debug logs.
        summary["predict_impl"] = m.predict_impl
        if m.artifact_digest is not None:
            summary["artifact_digest"] = m.artifact_digest
        if self.run_log is not None:
            self.run_log.emit("serve_latency", **summary)
        return summary

    def debug_traces(self) -> dict:
        """model name -> completed-trace ring (GET /debug/requests).
        Anonymous single-model servers key on "default"."""
        return {self.model_name or "default":
                self.stats.traces_snapshot()}

    def flush_traces(self, reason: str = "on_demand") -> int:
        """Flush the completed-trace ring into the run log as ONE
        schema-additive `serve_trace` event (on demand via
        GET /debug/requests?emit=1; the fleet also flushes on SLO
        breach). Returns the number of traces flushed (0 on an empty
        ring or a log-less engine — nothing is emitted then)."""
        traces = self.stats.traces_snapshot()
        if not traces or self.run_log is None:
            return 0
        extra = ({"model_name": self.model_name}
                 if self.model_name is not None else {})
        self.run_log.emit("serve_trace", traces=traces,
                          count=len(traces),
                          model_token=self._model.token,
                          reason=reason, **extra)
        return len(traces)

    def metrics_snapshot(self) -> dict:
        """Live, non-resetting state for the /metrics exposition
        (serve/metrics.py renders it): per-model latency histograms on
        the fixed ladder, live backlog, residency. Read-only — the
        /metrics vs /stats?emit=1 contract."""
        name = self.model_name or "default"
        return {
            "models": {name: {
                "hist": self.stats.metrics_state(),
                "backlog_rows": self._batcher.backlog_rows(),
                "slo": None,
            }},
            "resident_models": 1,
            "max_resident": None,
        }

    def health(self) -> dict:
        m = self._model
        return {
            "ok": True,
            "model_name": self.model_name,
            "model_token": m.token,
            "quantized": m.quantized,
            "quantize_tier": getattr(m, "quantize_tier", None),
            "predict_impl": m.predict_impl,
            "lut_max_abs_err": m.max_abs_err,
            "buckets": list(self.buckets),
            "express_lane": self.express_lane,
            "artifact_digest": m.artifact_digest,
            "aot": m.aot,
            **self.stats.snapshot(),
        }

    def close(self) -> None:
        self._batcher.close()
        self.emit_latency(reset=True)
        if self.run_log is not None:
            self.run_log.close()
