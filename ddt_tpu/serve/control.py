"""Fleet control plane: declarative config + registry-backed loading.

The configuration half of the fleet tier (ISSUE 15; fleet.py is the
dispatch half). Three jobs, all of them OFF the request hot loop:

- **parse** the two fleet config surfaces into `FleetSpec`s — the CLI's
  `--models a@prod,b@canary:weight=3` shorthand and the `--fleet-config
  fleet.json` file ({"models": [{"name", "ref", "weight", "tier",
  "max_batch", "raw", "slo_p99_ms"}, ...]} or a bare list) — with loud errors on
  duplicate names, unknown keys, and malformed values (the CLI wraps
  them SystemExit-clean like the registry group);
- **resolve** every reference at boot (registry name index or an
  artifact file on disk) so an unknown ref fails the `cli serve`
  command, not the first request hours later;
- **load**: `make_loader` builds the injected callable FleetEngine
  calls on handler threads — a registry ref restores through the
  zero-retrace AOT loader (ddt_tpu/registry/loader.py: eviction is
  cheap BECAUSE reload is a bounded cold-load, never a retrace), a
  file path builds a plain ServableModel (full prologue, documented as
  the non-registry mode).

This module does file I/O by design — it is the cli/http-layer side of
the serve-blocking-io contract, and FleetEngine only ever invokes the
loader on caller threads with no fleet lock held.
"""

from __future__ import annotations

import dataclasses
import json
import os

from ddt_tpu.serve.engine import TIER_IMPL, normalize_quantize
from ddt_tpu.serve.fleet import FleetEngine


class FleetConfigError(ValueError):
    """Malformed fleet configuration (duplicate name, unknown key,
    unresolvable reference, bad value) — always loud, always at boot
    or at the control-plane call, never at dispatch time."""


@dataclasses.dataclass(frozen=True)
class FleetSpec:
    """One fleet member, fully declarative: the registry reference (or
    artifact path), its dispatch weight, serving tier, and admission
    ladder. `tier=None` FOLLOWS the artifact (a quantized export serves
    its exported tier, an f32 export serves f32) — mixed-tier fleets
    come free from mixed artifacts."""

    name: str
    ref: str
    weight: float = 1.0
    tier: "str | None" = None
    max_batch: int = 256
    raw: bool = False
    #: per-request p99 latency objective in ms (ISSUE 17) — None means
    #: no SLO: no burn-rate tracking, no slo_breach events, and the
    #: health/metrics payloads stay byte-identical to pre-SLO output.
    slo_p99_ms: "float | None" = None
    #: champion this model SHADOWS (ISSUE 19) — a challenger scores the
    #: champion's dispatched batches off the response path
    #: (serve/drift.py.ShadowScorer). The shadow stays a normal fleet
    #: member (residency, direct requests by name) but receives none of
    #: the champion's traffic on the response path. None = not a shadow.
    shadow_of: "str | None" = None
    #: drift tracking tri-state (ISSUE 19): None AUTO-enables when the
    #: artifact carries a training reference histogram
    #: (mapper.ref_counts); True REQUIRES one (a reference-less artifact
    #: is a FleetConfigError at load, never a quiet no-op); False
    #: disables tracking even when a reference is present.
    drift: "bool | None" = None

    def __post_init__(self):
        if not self.name:
            raise FleetConfigError("fleet entry has an empty name")
        if self.weight <= 0:
            raise FleetConfigError(
                f"model {self.name!r}: weight must be > 0, got "
                f"{self.weight}")
        if self.max_batch < 1:
            raise FleetConfigError(
                f"model {self.name!r}: max_batch must be >= 1, got "
                f"{self.max_batch}")
        if self.slo_p99_ms is not None and self.slo_p99_ms <= 0:
            raise FleetConfigError(
                f"model {self.name!r}: slo_p99_ms must be > 0, got "
                f"{self.slo_p99_ms}")
        if self.shadow_of is not None and self.shadow_of == self.name:
            raise FleetConfigError(
                f"model {self.name!r}: cannot shadow itself")


_SPEC_KEYS = {"name", "ref", "model", "weight", "tier", "max_batch",
              "raw", "slo_p99_ms", "shadow_of", "drift"}


def _default_name(ref: str) -> str:
    """`a@prod` -> `a`; a file path -> its stem (`/x/model_b.npz` ->
    `model_b`)."""
    base = ref.split("@", 1)[0]
    if os.sep in base or base.endswith(".npz"):
        base = os.path.splitext(os.path.basename(base))[0]
    return base


def _coerce_bool(v, where: str, key: str) -> bool:
    """Strict flag parsing for the string surfaces (`--models
    m:raw=false` and POST /models JSON strings): bool('false') is True,
    so a naive cast would make every spelling ENABLE the flag."""
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "on"):
        return True
    if s in ("false", "0", "no", "off", ""):
        return False
    raise FleetConfigError(
        f"{where}: {key} must be a boolean (true/false), got {v!r}")


def coerce_spec(d: dict, where: str) -> FleetSpec:
    unknown = set(d) - _SPEC_KEYS
    if unknown:
        raise FleetConfigError(
            f"{where}: unknown fleet entry key(s) "
            f"{', '.join(sorted(unknown))} (have: "
            f"{', '.join(sorted(_SPEC_KEYS - {'model'}))})")
    ref = d.get("ref") or d.get("model")
    if not ref:
        raise FleetConfigError(f"{where}: fleet entry needs a 'ref' "
                               "(registry reference or artifact path)")
    tier = d.get("tier")
    try:
        tier = normalize_quantize(tier) if tier is not None else None
    except ValueError as e:
        raise FleetConfigError(f"{where}: {e}") from e
    slo = d.get("slo_p99_ms")
    if slo is not None:
        # Loud junk rejection at parse time: "fast", "", "5ms" all land
        # here — float('5ms') raising late would blame the wrong layer.
        try:
            slo = float(slo)
        except (TypeError, ValueError):
            raise FleetConfigError(
                f"{where}: slo_p99_ms must be a positive number of "
                f"milliseconds, got {d.get('slo_p99_ms')!r}") from None
    drift = d.get("drift")
    if drift is not None:
        drift = _coerce_bool(drift, where, "drift")
    shadow_of = d.get("shadow_of")
    try:
        return FleetSpec(
            name=str(d.get("name") or _default_name(str(ref))),
            ref=str(ref),
            weight=float(d.get("weight", 1.0)),
            tier=tier,
            max_batch=int(d.get("max_batch", 256)),
            raw=_coerce_bool(d.get("raw", False), where, "raw"),
            slo_p99_ms=slo,
            shadow_of=(str(shadow_of) if shadow_of else None),
            drift=drift)
    except (TypeError, ValueError) as e:
        raise FleetConfigError(f"{where}: {e}") from e


def parse_models_arg(arg: str) -> "list[FleetSpec]":
    """`--models` shorthand: comma-separated entries, each
    `ref[:key=value]*` — e.g. `a@prod,b@canary:weight=3,
    c@v2:tier=int4:max_batch=64:name=tiny`. The ref's name part (before
    `@`) is the model name unless `name=` overrides it."""
    specs = []
    for i, entry in enumerate(s.strip() for s in arg.split(",")):
        if not entry:
            raise FleetConfigError(
                f"--models entry {i} is empty (stray comma?)")
        parts = entry.split(":")
        d: dict = {"ref": parts[0]}
        for kv in parts[1:]:
            if "=" not in kv:
                raise FleetConfigError(
                    f"--models entry {parts[0]!r}: expected key=value "
                    f"after ':', got {kv!r}")
            k, v = kv.split("=", 1)
            d[k.strip()] = v.strip()
        specs.append(coerce_spec(d, f"--models entry {parts[0]!r}"))
    return specs


def load_fleet_config(path: str) -> "list[FleetSpec]":
    """`--fleet-config` file: JSON — either {"models": [...]} (extra
    top-level keys refused loudly) or a bare list of entries."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise FleetConfigError(f"fleet config {path}: {e}") from e
    if isinstance(doc, dict):
        unknown = set(doc) - {"models"}
        if unknown:
            raise FleetConfigError(
                f"fleet config {path}: unknown top-level key(s) "
                f"{', '.join(sorted(unknown))} (expected 'models')")
        entries = doc.get("models")
    else:
        entries = doc
    if not isinstance(entries, list) or not entries:
        raise FleetConfigError(
            f"fleet config {path}: 'models' must be a non-empty list")
    out = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise FleetConfigError(
                f"fleet config {path}: entry {i} must be an object")
        out.append(coerce_spec(e, f"{path} entry {i}"))
    return out


def validate_specs(specs: "list[FleetSpec]") -> "list[FleetSpec]":
    if not specs:
        raise FleetConfigError(
            "fleet has no models (pass --models and/or --fleet-config)")
    seen: dict = {}
    for s in specs:
        if s.name in seen:
            raise FleetConfigError(
                f"duplicate model name {s.name!r} "
                f"({seen[s.name].ref!r} vs {s.ref!r}); give one of "
                "them an explicit name=")
        seen[s.name] = s
    # Shadow topology (ISSUE 19): every challenger names a champion in
    # THIS fleet, and chains are refused — a shadow of a shadow would
    # compare against scores that were themselves off-path samples.
    for s in specs:
        if s.shadow_of is None:
            continue
        champ = seen.get(s.shadow_of)
        if champ is None:
            raise FleetConfigError(
                f"model {s.name!r}: shadow_of={s.shadow_of!r} names no "
                f"model in this fleet (have: "
                f"{', '.join(sorted(seen))})")
        if champ.shadow_of is not None:
            raise FleetConfigError(
                f"model {s.name!r}: shadow_of={s.shadow_of!r} is itself "
                f"a shadow (of {champ.shadow_of!r}); shadow chains are "
                "not supported")
    challengers: dict = {}
    for s in specs:
        if s.shadow_of is None:
            continue
        prev = challengers.setdefault(s.shadow_of, s.name)
        if prev != s.name:
            raise FleetConfigError(
                f"model {s.shadow_of!r} has two challengers "
                f"({prev!r} and {s.name!r}); one challenger per "
                "champion")
    return specs


def resolve_specs(specs, registry_root: "str | None") -> dict:
    """Resolve every ref at boot — {name: digest | "file"} — so unknown
    references fail the command, not the first request. Registry refs
    need `registry_root`; artifact paths just need to exist."""
    out = {}
    for spec in specs:
        if os.path.exists(spec.ref):
            out[spec.name] = "file"
            continue
        if registry_root is None:
            raise FleetConfigError(
                f"model {spec.name!r}: ref {spec.ref!r} is not a file, "
                "and no --registry was given so registry references "
                "cannot resolve")
        from ddt_tpu.registry import Registry, RegistryError

        try:
            out[spec.name] = Registry(registry_root).resolve(spec.ref)
        except RegistryError as e:
            raise FleetConfigError(
                f"model {spec.name!r}: {e}") from e
    return out


def make_loader(registry_root: "str | None", backend_name: str,
                run_log=None):
    """The FleetEngine `loader(spec)` callable: registry refs restore
    through the zero-retrace AOT loader (artifact events land in the
    shared run log), file refs build a plain ServableModel. Always runs
    on a caller/handler thread — never the dispatcher."""

    def loader(spec: FleetSpec):
        if os.path.exists(spec.ref):
            from ddt_tpu import api
            from ddt_tpu.backends import get_backend
            from ddt_tpu.config import TrainConfig
            from ddt_tpu.serve.engine import ServableModel, default_buckets

            bundle = api.load_model(spec.ref)
            cfg = TrainConfig(
                backend=backend_name, loss=bundle.ensemble.loss,
                n_classes=max(bundle.ensemble.n_classes, 2),
                predict_impl=TIER_IMPL.get(spec.tier, "auto"))
            return ServableModel(
                bundle, get_backend(cfg), quantize=spec.tier,
                buckets=default_buckets(spec.max_batch), raw=spec.raw)
        if registry_root is None:
            raise FleetConfigError(
                f"model {spec.name!r}: ref {spec.ref!r} is not a file "
                "and this fleet has no registry")
        from ddt_tpu.registry import loader as reg_loader

        report = reg_loader.load_servable(
            registry_root, spec.ref, quantize=spec.tier,
            raw=spec.raw, backend=backend_name, run_log=run_log)
        return report.model

    return loader


def build_fleet(specs, *, registry: "str | None" = None,
                backend: str = "tpu", max_wait_ms: float = 1.0,
                max_resident: "int | None" = None, run_log=None,
                express_lane: bool = True, preload: bool = True,
                request_traces: bool = True) -> FleetEngine:
    """Specs -> a running FleetEngine: validate, resolve every ref
    loudly, build the loader over the registry, and (by default) make
    the first `max_resident` models resident so boot-time failures are
    boot-time errors. ONE RunLog is shared by the loader's artifact
    events and the engine's serving events (per-log monotonic seq —
    the merge invariant)."""
    from ddt_tpu.telemetry.events import RunLog

    run_log = RunLog.coerce(run_log)
    specs = validate_specs(list(specs))
    resolve_specs(specs, registry)
    engine = FleetEngine(
        specs, make_loader(registry, backend, run_log=run_log),
        max_wait_ms=max_wait_ms, max_resident=max_resident,
        run_log=run_log, express_lane=express_lane,
        request_traces=request_traces)
    if preload:
        budget = len(specs) if max_resident is None else max_resident
        try:
            for spec in specs[:budget]:
                engine.n_features_for(spec.name)   # load + warm, loudly
        except BaseException:
            engine.close()                         # don't leak the thread
            raise
    return engine
