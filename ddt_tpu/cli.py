"""CLI (layer L8): train / predict / bench with the backend flag.

SURVEY.md §1 L8 + [BASELINE] "backend selectable by flag":

    python -m ddt_tpu.cli train   --backend=tpu --dataset=higgs --rows=1000000
    python -m ddt_tpu.cli predict --model=ens.npz --dataset=higgs --rows=10000
    python -m ddt_tpu.cli bench   --kernel=histogram --backend=tpu

Datasets are the BASELINE.json configs, backed by seeded synthetic generators
(data/datasets.py) since this environment has no network; a --data=path.npz
escape hatch loads (X, y) from disk.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys
import time

import numpy as np

from ddt_tpu import api
from ddt_tpu.config import BACKENDS, LOSSES, TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.models.tree import TreeEnsemble


def _parse_mesh_shape(v: "str | None") -> "tuple | None":
    """--mesh-shape "Pr,Pf" -> (Pr, Pf) (TrainConfig.mesh_shape), None
    passes through. Validation beyond the parse (>= 1, conflicts with
    --partitions/--feature-partitions) lives in TrainConfig."""
    if v is None:
        return None
    parts = [p.strip() for p in str(v).split(",")]
    try:
        pr, pf = (int(p) for p in parts)
    except (TypeError, ValueError):
        raise SystemExit(
            f"--mesh-shape must be 'Pr,Pf' (two integers), got {v!r}")
    return (pr, pf)


def _positive_int(v: str) -> int:
    i = int(v)
    if i < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {i}")
    return i


def _load_dataset(args, encoder=None, n_features=None):
    """(X, y, n_classes, encoder) for the named dataset config.

    `encoder` is the training-time CategoricalEncoder; when given (predict
    path) categorical columns are transformed with IT, never refitted on the
    scoring data. `n_features` (predict path) pins the file loader's width
    to the model's. The returned encoder is non-None only for datasets with
    categorical columns (criteo)."""
    if args.data:
        X, y = datasets.load_file(
            args.data, label_col=getattr(args, "label_col", "auto"),
            # Regression targets pass through verbatim; classification text
            # conventions (-1/+1, 1-based classes) normalize to 0-based.
            normalize_labels=None if args.loss != "mse" else False,
            n_features=n_features,
        )
        return (X, y,
                int(y.max()) + 1 if args.loss == "softmax" else 2, None)
    if args.dataset == "higgs":
        X, y = datasets.synthetic_binary(args.rows, seed=args.seed)
        return X, y, 2, None
    if args.dataset == "covertype":
        X, y = datasets.synthetic_multiclass(args.rows, seed=args.seed)
        return X, y, 7, None
    if args.dataset == "criteo":
        from ddt_tpu.data.categorical import fit_categorical_encoder

        Xn, Xc, y = datasets.synthetic_ctr(args.rows, seed=args.seed)
        if encoder is None:
            encoder = fit_categorical_encoder(Xc, n_bins=args.bins)
        X = np.concatenate(
            [Xn, encoder.transform(Xc).astype(np.float32)], axis=1,
        )
        return X, y, 2, encoder
    if args.dataset == "regression":
        X, y = datasets.synthetic_regression(args.rows, seed=args.seed)
        return X, y, 1, None
    raise SystemExit(f"unknown dataset {args.dataset!r}")


def _predict_streaming(args, bundle) -> int:
    """`predict --stream-dir=D`: score npz shards chunk-by-chunk in
    O(chunk) host memory (the 10M-row x 1000-tree config at beyond-RAM
    scale). Scores land as per-shard .npy files under --out (a directory
    here) — a 10B-row score vector has no business being concatenated in
    host memory either."""
    from ddt_tpu.data import chunks as chunks_mod

    ens = bundle.ensemble
    src = chunks_mod.directory_chunks(args.stream_dir)
    if bundle.encoder is not None and not src.binned:
        # Shards are arbitrary files — nothing says which columns are
        # raw categorical ids, so re-encoding here is impossible and
        # quantile-binning raw ids would silently garbage every
        # categorical split. Same refuse-loudly contract as the
        # in-memory path's encoder checks.
        raise SystemExit(
            f"{args.model} carries a categorical encoder but the shards "
            "hold raw floats; score via the in-memory predict path, or "
            "shard data whose categorical columns are already "
            "encoder.transform'ed AND pre-binned (uint8)."
        )
    if not src.binned and bundle.mapper is None \
            and not ens.has_raw_thresholds:
        raise SystemExit(
            f"{args.model} carries neither a bin mapper nor raw "
            "thresholds; retrain with the current CLI (which saves the "
            "full artifact) or shard pre-binned uint8 data."
        )
    if src.binned and src.n_features != ens.n_features:
        raise SystemExit(
            f"shards have {src.n_features} features but the model was "
            f"trained with {ens.n_features}")
    cfg = TrainConfig(backend=args.backend, loss=ens.loss,
                      n_classes=max(ens.n_classes, 2),
                      n_partitions=max(1, getattr(args, "partitions", 1)))
    out_dir = args.out or "scores"
    os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()

    def sink(c, scores):
        np.save(os.path.join(out_dir, f"scores_{c:05d}.npy"), scores)

    if src.binned:
        # Binned shards + any backend: the double-buffered scoring
        # pipeline (streaming.predict_streaming) — the next shard's read
        # + upload rides under the current shard's traversal, scores
        # drain asynchronously, and the compiled ensemble stays resident
        # across shards. Per-shard outputs keep host memory O(chunk).
        from ddt_tpu.backends import get_backend
        from ddt_tpu.streaming import predict_streaming

        rows = predict_streaming(
            src, src.n_chunks, ens, backend=get_backend(cfg),
            raw=False, sink=sink)
    else:
        rows = 0
        for c in range(src.n_chunks):
            X, _ = src(c)
            if bundle.mapper is not None:
                scores = api.predict(ens, X, mapper=bundle.mapper, cfg=cfg)
            else:   # raw-value thresholds traversal (mapper-less artifact)
                scores = api.predict(ens, X, cfg=cfg)
            sink(c, scores)
            rows += len(scores)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "cmd": "predict", "backend": args.backend, "rows": rows,
        "trees": ens.n_trees, "streamed_chunks": src.n_chunks,
        "wallclock_s": round(dt, 3),
        "rows_per_sec": round(rows / dt, 1),
        "out_dir": out_dir,
    }))
    return 0


def _capture_window(args):
    """telemetry.profiler.CaptureWindow from --xprof-dir/--xprof-rounds,
    or None — ONE construction home for the in-memory and streamed train
    paths (a bad window spec exits cleanly either way)."""
    if not getattr(args, "xprof_dir", None):
        return None
    from ddt_tpu.telemetry.profiler import CaptureWindow

    try:
        return CaptureWindow(args.xprof_dir, args.xprof_rounds)
    except ValueError as e:
        raise SystemExit(f"--xprof-rounds: {e}") from e


def _seeded_split(X, y, frac: float, seed: int):
    """The seeded held-out row split — ONE home for both the in-memory and
    streamed train paths, so their validation semantics cannot drift.
    Returns (X_train, y_train, X_val, y_val)."""
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(y))
    k = int(len(y) * frac)
    if k < 1:
        raise SystemExit("--valid-frac holds out zero rows")
    va, tr = idx[:k], idx[k:]
    return X[tr], y[tr], X[va], y[va]


def _train_streaming(args, X, y, cfg, encoder, status=None) -> int:
    """`train --stream-chunks=N | --stream-dir=D`: the BASELINE config-5
    path from the CLI. With --stream-dir, training streams npz shards
    from disk in O(chunk) host memory end to end (data.chunks); with
    --stream-chunks, the loaded dataset is binned chunk-by-chunk into an
    on-disk uint8 cache and streamed back from it — either way no binned
    matrix is ever host-resident."""
    import shutil
    import tempfile

    # Sampling configs stream since round 5 (stateless counter-based
    # masks, ops/sampling) and --profile/--run-log since the telemetry
    # PR (fit_streaming wires its own PhaseTimer); only the XLA trace
    # capture stays in-memory-only.
    unsupported = [
        (args.trace_dir is not None, "--trace-dir"),
    ]
    bad = [flag for cond, flag in unsupported if cond]
    if bad:
        raise SystemExit(
            f"--stream-chunks does not compose with {', '.join(bad)}"
        )
    t0 = time.perf_counter()
    tmp_cache = None
    cache_root = args.stream_cache_dir
    if cache_root is None:
        tmp_cache = tempfile.mkdtemp(prefix="ddt_binned_")
        cache_root = tmp_cache
    window = _capture_window(args)
    # Coerce the run log HERE so the run_id fit_streaming derives (and
    # binds on the instance) survives for the saved model's manifest —
    # the same provenance stamp the in-memory train path writes.
    from ddt_tpu.telemetry.events import RunLog

    run_log = RunLog.coerce(args.run_log)
    try:
        ens, history, mapper, rows, n_chunks, chunk_rows_max = \
            _stream_fit(args, X, y, cfg, cache_root, window,
                        run_log=run_log, status=status)
    except NotImplementedError as e:   # e.g. feature-parallel streaming
        raise SystemExit(str(e)) from e
    finally:
        # tmp cache cleanup covers EVERY failure mode, including a death
        # mid-way through writing the (potentially huge) binned cache.
        if tmp_cache is not None:
            shutil.rmtree(tmp_cache, ignore_errors=True)
        if run_log is not None:
            run_log.close()
    dt = time.perf_counter() - t0
    if mapper is not None:
        from ddt_tpu.reference.numpy_trainer import _fill_raw_thresholds

        _fill_raw_thresholds(ens, mapper)
    api.save_model(args.out, ens, mapper=mapper, encoder=encoder,
                   run_id=run_log.run_id if run_log else None, cfg=cfg)
    out = {
        "cmd": "train", "backend": args.backend, "rows": rows,
        "trees": ens.n_trees, "depth": cfg.max_depth,
        "streamed_chunks": n_chunks,
        "chunk_rows": chunk_rows_max,
        "wallclock_s": round(dt, 3),
        "model": args.out,
    }
    if history:
        from ddt_tpu.utils.metrics import GREATER_IS_BETTER

        mk = next(k for k in history[0] if k.startswith("valid_"))
        sign = 1.0 if GREATER_IS_BETTER[mk[len("valid_"):]] else -1.0
        bi = int(np.argmax([sign * r[mk] for r in history]))
        out["best_round"] = history[bi]["round"]
        out["best_score"] = round(history[bi][mk], 6)
    if args.run_log:
        out["run_log"] = args.run_log
    if window is not None:
        # Same stamp the in-memory path prints: scripts locating the
        # capture read it from the train record, not just the manifest.
        out["xprof_dir"] = window.trace_dir
    print(json.dumps(out))
    return 0


def _stream_fit(args, X, y, cfg, cache_root, window=None, run_log=None,
                status=None):
    """Chunk-source construction + fit_streaming for _train_streaming
    (separated so its caller's finally-cleanup wraps the WHOLE cache
    lifecycle). Returns (ens, history, mapper, rows, n_chunks,
    chunk_rows_max)."""
    from ddt_tpu.data import chunks as chunks_mod
    from ddt_tpu.data.quantizer import fit_bin_mapper_streaming
    from ddt_tpu.streaming import (binned_chunks, fit_streaming,
                                   validate_mapper_config)

    def _cached_binned(raw_fn, n, mapper, sub):
        """Raw chunks -> uint8 cache shards on disk (transform once);
        falls through to re-binning reads when caching is disabled."""
        if args.stream_cache_dir == "":
            return binned_chunks(raw_fn, mapper, cfg)
        return chunks_mod.write_binned_cache(
            raw_fn, n, mapper, os.path.join(cache_root, sub))

    if args.stream_dir:
        # True out-of-core: npz shards streamed from disk, O(chunk) host
        # memory end to end — nothing was loaded by _load_dataset.
        if args.stream_chunks:
            raise SystemExit(
                "--stream-dir reads its chunk count from the directory; "
                "drop --stream-chunks")
        raw = chunks_mod.directory_chunks(args.stream_dir)
        n_total = raw.n_chunks
        n_valid = 0
        if args.valid_frac > 0:
            # Chunk-granularity holdout: the LAST ceil(frac*n) shards.
            n_valid = int(np.ceil(n_total * args.valid_frac))
            if n_valid >= n_total:
                raise SystemExit(
                    f"--valid-frac={args.valid_frac} holds out all "
                    f"{n_total} shards; nothing left to train on")
        elif args.early_stop is not None:
            raise SystemExit("--early-stop requires --valid-frac")
        n_chunks = n_total - n_valid

        def raw_train(c):
            return raw(c)

        raw_train.labels = raw.labels
        raw_train.n_features = raw.n_features

        def raw_valid(c):
            return raw(n_chunks + c)

        raw_valid.labels = lambda c: raw.labels(n_chunks + c)

        lens = [len(raw.labels(c)) for c in range(n_total)]
        rows = sum(lens[:n_chunks])
        chunk_rows_max = max(lens[:n_chunks])
        if cfg.loss == "softmax":
            ymax = max(int(raw.labels(c).max()) for c in range(n_total))
            cfg = cfg.replace(n_classes=max(cfg.n_classes, ymax + 1))
        if raw.binned:
            # Pre-binned uint8 shards (e.g. a binned cache, or the stress
            # generator's output): no mapper — the artifact scores binned
            # input only.
            mapper = None
            chunk_fn, valid_chunk_fn = raw_train, (
                raw_valid if n_valid else None)
        else:
            mapper = fit_bin_mapper_streaming(
                raw_train, n_chunks, n_bins=cfg.n_bins, seed=cfg.seed,
                missing_policy=cfg.missing_policy,
                cat_features=cfg.cat_features,
            )
            validate_mapper_config(mapper, cfg)
            chunk_fn = _cached_binned(raw_train, n_chunks, mapper, "train")
            valid_chunk_fn = (
                _cached_binned(raw_valid, n_valid, mapper, "valid")
                if n_valid else None)
    else:
        # Loaded dataset (--dataset/--data): held-out validation uses the
        # same seeded row split as the in-memory path, then BOTH splits
        # stream through the on-disk uint8 cache — no binned matrix is
        # ever host-resident (round-2 verdict item 4).
        Xv = yv = None
        if args.valid_frac > 0:
            X, y, Xv, yv = _seeded_split(X, y, args.valid_frac, args.seed)
        elif args.early_stop is not None:
            raise SystemExit("--early-stop requires --valid-frac")
        n_chunks = args.stream_chunks
        rows = len(y)
        if n_chunks > rows:
            raise SystemExit(
                f"--stream-chunks={n_chunks} exceeds the row count "
                f"({rows}); empty chunks are not allowed"
            )
        # Truncated-linspace boundaries: sizes differ by at most one,
        # never empty given the guard above (ragged chunks are supported —
        # each size compiles its own program).
        bounds = np.linspace(0, rows, n_chunks + 1).astype(np.int64)
        chunk_rows_max = int((bounds[1:] - bounds[:-1]).max())

        def raw_fn(c):
            return X[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

        mapper = fit_bin_mapper_streaming(
            raw_fn, n_chunks, n_bins=cfg.n_bins, seed=cfg.seed,
            missing_policy=cfg.missing_policy, cat_features=cfg.cat_features,
        )
        validate_mapper_config(mapper, cfg)
        chunk_fn = _cached_binned(raw_fn, n_chunks, mapper, "train")

        valid_chunk_fn = None
        n_valid = 0
        if Xv is not None:
            # Val chunk sizes track the train chunk size (each distinct
            # size compiles its own device program).
            n_valid = max(1, int(np.ceil(
                len(yv) / max(1, -(-rows // n_chunks)))))
            vbounds = np.linspace(0, len(yv), n_valid + 1).astype(np.int64)

            def raw_vfn(c):
                return (Xv[vbounds[c]:vbounds[c + 1]],
                        yv[vbounds[c]:vbounds[c + 1]])

            valid_chunk_fn = _cached_binned(raw_vfn, n_valid, mapper,
                                            "valid")

    raw_cache = getattr(args, "stream_device_cache", "auto")
    if raw_cache == "auto":
        dev_cache: "bool | int" = True
    elif raw_cache == "off":
        dev_cache = False
    else:
        try:
            dev_cache = int(raw_cache)
        except ValueError:
            raise SystemExit(
                f"--stream-device-cache must be 'auto', 'off', or a byte "
                f"count, got {raw_cache!r}")

    history: list = []
    ens = fit_streaming(chunk_fn, n_chunks, cfg,
                        checkpoint_dir=args.checkpoint_dir,
                        checkpoint_every=args.checkpoint_every,
                        valid_chunk_fn=valid_chunk_fn,
                        n_valid_chunks=n_valid,
                        eval_metric=args.metric,
                        early_stopping_rounds=args.early_stop,
                        history=history,
                        device_chunk_cache=dev_cache,
                        run_log=run_log,
                        profile=args.profile,
                        profiler_window=window,
                        status=status)
    return ens, history, mapper, rows, n_chunks, chunk_rows_max


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--backend", choices=BACKENDS, default="tpu",
                   help="device backend (the [BASELINE] flag)")
    p.add_argument("--dataset",
                   choices=["higgs", "covertype", "criteo", "regression"],
                   default="higgs")
    p.add_argument("--data", default=None,
                   help="path to a dataset file: .npz with arrays X,y / "
                        ".csv[.gz] / libsvm text (overrides --dataset)")
    p.add_argument("--label-col", choices=["auto", "first", "last"],
                   default="auto",
                   help="which CSV column is the label (use 'last' for "
                        "regression CSVs — a float target defeats auto)")
    p.add_argument("--rows", type=int, default=100_000)
    p.add_argument("--bins", type=int, default=255)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--loss", choices=LOSSES, default=None,
                   help="default: inferred from dataset")


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    try:    # our process: cache XLA compiles (keep jax a soft dependency —
        # cpu-backend CLI use must work without it)
        from ddt_tpu.backends.tpu import enable_persistent_compile_cache

        enable_persistent_compile_cache()
    except ImportError:
        pass
    ap = argparse.ArgumentParser(prog="ddt_tpu",
                                 description="TPU-native distributed GBDT")
    sub = ap.add_subparsers(dest="cmd", required=True)

    tp = sub.add_parser("train", help="train an ensemble")
    _add_common(tp)
    tp.add_argument("--trees", type=int, default=100)
    tp.add_argument("--depth", type=int, default=6)
    tp.add_argument("--lr", type=float, default=0.1)
    tp.add_argument("--partitions", type=int, default=1,
                    help="row partitions over the device mesh")
    tp.add_argument("--feature-partitions", type=int, default=1,
                    help="column partitions (TP-analog mesh axis); uses "
                         "partitions x feature-partitions devices")
    tp.add_argument("--host-partitions", type=int, default=1,
                    help="cross-slice DCN mesh axis for multi-host pods; "
                         "row shards span host-partitions x partitions")
    tp.add_argument("--mesh-shape", default=None, metavar="Pr,Pf",
                    help="declarative 2D (rows x features) mesh shape, "
                         "e.g. 4,2 — the one-flag spelling of "
                         "--partitions Pr --feature-partitions Pf "
                         "(TrainConfig.mesh_shape; conflicts with "
                         "setting those flags to different values)")
    tp.add_argument("--multihost-coordinator", default=None,
                    help="host:port of process 0 — runs jax.distributed."
                         "initialize before any device use, making "
                         "jax.devices() the GLOBAL pod device list (run "
                         "the SAME command on every host). On TPU pods "
                         "with auto-discovery, pass --multihost-processes "
                         "alone. Every process writes --out (the fetched "
                         "ensembles are replicas; use per-process paths "
                         "on a shared FS if you prefer)")
    tp.add_argument("--multihost-processes", type=int, default=None,
                    help="total process count for --multihost-coordinator")
    tp.add_argument("--multihost-id", type=int, default=None,
                    help="this process's id in [0, multihost-processes)")
    tp.add_argument("--missing", choices=["zero", "learn"], default="zero",
                    help="NaN policy: zero = bin 0; learn = reserved NaN "
                         "bin + learned per-split default direction")
    tp.add_argument("--cat-splits", choices=["ordinal", "onehot"],
                    default="ordinal",
                    help="categorical split type for the criteo config's "
                         "encoded columns: ordinal (frequency-rank bins, "
                         "bin<=t) or onehot (one-vs-rest, bin==k)")
    tp.add_argument("--profile", action="store_true",
                    help="log a per-phase wallclock breakdown (adds device "
                         "barriers; rounds run slower than unprofiled)")
    tp.add_argument("--trace-dir", default=None,
                    help="capture a jax.profiler trace here (TensorBoard/"
                         "Perfetto; device spans carry the same ddt:<phase> "
                         "names as --run-log phase timings)")
    tp.add_argument("--xprof-dir", default=None,
                    help="capture a PROGRAMMATIC jax.profiler trace around "
                         "the --xprof-rounds window only (vs --trace-dir's "
                         "whole-run capture); lands in <dir>/run_<run_id> "
                         "and the window + path are stamped into the run "
                         "manifest so the trace and the run log cross-"
                         "reference by run id (docs/OBSERVABILITY.md)")
    tp.add_argument("--xprof-rounds", default="2:3",
                    help="1-based inclusive round window LO:HI for "
                         "--xprof-dir (default 2:3 — round 1's warmup "
                         "compiles are skipped by starting later)")
    tp.add_argument("--run-log", default=None,
                    help="write a structured JSONL telemetry run log here "
                         "(run manifest, per-round records, phase timings, "
                         "device counters; render with the `report` "
                         "subcommand — docs/OBSERVABILITY.md)")
    tp.add_argument("--status-port", type=int, default=None,
                    help="serve a read-only live training status daemon on "
                         "127.0.0.1:<port> for the duration of the run "
                         "(0 = ephemeral; the bound port is printed as a "
                         "statusd JSON line at boot): GET /healthz "
                         "(progress/ETA JSON), /metrics (Prometheus text), "
                         "/debug/rounds (recent-round ring) — "
                         "docs/OBSERVABILITY.md; no flag = zero overhead, "
                         "nothing is imported or allocated")
    tp.add_argument("--subsample", type=float, default=1.0,
                    help="row fraction per boosting round (bagging)")
    tp.add_argument("--colsample-bytree", type=float, default=1.0,
                    help="feature fraction per tree")
    tp.add_argument("--fused-block-rounds", type=_positive_int, default=100,
                    help="max boosting rounds per fused device dispatch "
                         "(>= 1); tune DOWN if a watchdogged remote "
                         "runtime kills long device programs "
                         "(TrainConfig.fused_block_rounds)")
    tp.add_argument("--hist-impl", default="auto",
                    choices=["auto", "matmul", "segment", "pallas"])
    tp.add_argument("--hist-subtraction", default="auto",
                    choices=["auto", "on", "off"],
                    help="sibling-subtraction trick in the level loop "
                         "(left children built, right = parent - left); "
                         "auto = on only on a real TPU chip "
                         "(TrainConfig.hist_subtraction)")
    tp.add_argument("--split-comms", default="auto",
                    choices=["auto", "allreduce", "reduce_scatter"],
                    help="split-finding collective (parallel/comms.py): "
                         "reduce_scatter merges one F/P feature slab per "
                         "row shard and all_gathers the tiny winner "
                         "tuples; auto = reduce_scatter when a row mesh "
                         "is live (TrainConfig.split_comms)")
    tp.add_argument("--hist-comms-dtype", default="f32",
                    choices=["f32", "bf16", "int32_fixed"],
                    help="histogram collective wire dtype (opt-in): bf16 "
                         "halves payload bytes; int32_fixed makes the "
                         "N-partition merge bit-stable via an integer "
                         "reduction (TrainConfig.hist_comms_dtype)")
    tp.add_argument("--hist-comms-slabs", type=int, default=0,
                    help="feature slabs for the pipelined build+collective "
                         "overlap; 0 = auto (pipelined on a real TPU "
                         "mesh), 1 = off (TrainConfig.hist_comms_slabs)")
    tp.add_argument("--grad-dtype", default="f32",
                    choices=["f32", "int16", "int8"],
                    help="quantized-gradient training (opt-in): g/h "
                         "discretized once per round onto one shared "
                         "grid with seeded stochastic "
                         "rounding; histograms/merges run in exact "
                         "int32 arithmetic — 4x (int8) / 2x (int16) "
                         "less g/h HBM traffic, sibling subtraction "
                         "exact everywhere (TrainConfig.grad_dtype)")
    tp.add_argument("--stream-chunks", type=int, default=0,
                    help="train via the streaming path (BASELINE config 5) "
                         "with the dataset split into this many chunks: "
                         "quantizer fitted by streamed reservoir sample, "
                         "per-chunk histogram accumulation, boosting state "
                         "device-resident on device backends")
    tp.add_argument("--stream-dir", default=None,
                    help="train out-of-core from a directory of npz chunk "
                         "shards (chunk_00000.npz ... with arrays X, y — "
                         "cut them with data.chunks.shard_file/"
                         "shard_arrays); O(chunk) host memory end to end. "
                         "Overrides --dataset/--data")
    tp.add_argument("--stream-cache-dir", default=None,
                    help="directory for the streamed paths' on-disk uint8 "
                         "binned-chunk cache (default: a temp dir deleted "
                         "after training; pass '' to disable caching and "
                         "re-bin chunks on every read)")
    tp.add_argument("--stream-device-cache", default="auto",
                    help="device-resident chunk cache for the streamed "
                         "paths: 'auto' (cache binned chunks in device "
                         "memory up to a ~6 GiB budget — every pass after "
                         "the first reads HBM instead of re-paying the "
                         "host->device link), 'off', or a byte budget")
    tp.add_argument("--config", default=None,
                    help="YAML/JSON file of TrainConfig fields; values in "
                         "the file override the corresponding flags")
    tp.add_argument("--out", default="ensemble.npz")
    tp.add_argument("--checkpoint-dir", default=None)
    tp.add_argument("--checkpoint-every", type=_positive_int, default=25,
                    help="write a checkpoint every K boosting rounds (>= 1)")
    tp.add_argument("--fault-plan", default=None,
                    help="JSON fault-injection plan (the chaos harness, "
                         "docs/ROBUSTNESS.md): fires named faults at the "
                         "real seams — torn checkpoint write, stream-read "
                         "IOError, multihost-init timeout, histogram OOM, "
                         "straggler delay — deterministically, so recovery "
                         "is a tested property; no plan = zero overhead")
    tp.add_argument("--straggler-repartition", action="store_true",
                    help="act on the straggler watchdog: rotate row-shard "
                         "-> device assignment at the next checkpoint "
                         "boundary when one device persistently straggles "
                         "(needs --run-log on a multi-partition run; "
                         "models are unchanged by construction — "
                         "docs/ROBUSTNESS.md)")
    tp.add_argument("--valid-frac", type=float, default=0.0,
                    help="hold out this fraction as a validation set")
    tp.add_argument("--metric", default=None,
                    help="validation metric (auc/accuracy/rmse/logloss)")
    tp.add_argument("--early-stop", type=int, default=None,
                    help="stop after this many rounds without improvement")

    pp = sub.add_parser("predict", help="score a batch with a saved ensemble")
    _add_common(pp)
    pp.add_argument("--model", required=True)
    pp.add_argument("--quantized", nargs="?", const="int8", default=None,
                    choices=["int8", "int4"],
                    help="score through the quantized TreeLUT ladder "
                         "(docs/SERVING.md): bare flag = the int8 tier "
                         "(cfg.predict_impl='lut': int8 thresholds + "
                         "fp16 leaf tables, ~4x less HBM traffic per "
                         "request); 'int4' = the bit-packed tier "
                         "(cfg.predict_impl='lut4': two-nibbles-per-"
                         "byte leaf tables + per-tree scales, half the "
                         "int8 tier's resident bytes again). Leaf "
                         "values stay within the tables' documented "
                         "max-abs-error bound of f32")
    pp.add_argument("--partitions", type=int, default=1,
                    help="row-shard scoring over this many chips "
                         "(parallel.mesh row mesh; trees replicate, each "
                         "chip traverses its own rows)")
    pp.add_argument("--out", default=None, help="write scores to this .npy "
                    "(with --stream-dir: a DIRECTORY of per-shard "
                    "scores_NNNNN.npy files)")
    pp.add_argument("--stream-dir", default=None,
                    help="score a directory of npz chunk shards "
                         "out-of-core, O(chunk) host memory (BASELINE "
                         "config 4 at beyond-RAM scale); overrides "
                         "--dataset/--data")

    sv = sub.add_parser(
        "serve",
        help="persistent low-latency scoring server (docs/SERVING.md): "
             "device-resident compiled model, admission-batched request "
             "coalescing, zero-downtime hot swap, serve_latency SLO "
             "telemetry")
    sv.add_argument("--model", default=None,
                    help="model artifact to serve: an api.save_model "
                         ".npz path, or — with --registry — a registry "
                         "reference (name, name@version, name@tag, or "
                         "digest); hot-swap later via POST /swap. "
                         "Required unless a FLEET is configured via "
                         "--models/--fleet-config")
    sv.add_argument("--models", default=None,
                    help="FLEET mode (docs/SERVING.md \"Fleet\"): "
                         "comma-separated model entries, each "
                         "ref[:key=value]* — e.g. "
                         "'a@prod,b@canary:weight=3,c@v2:tier=int4'. "
                         "Keys: name, weight, tier, max_batch, raw, "
                         "slo_p99_ms (per-request p99 latency "
                         "objective in ms — enables burn-rate "
                         "tracking + slo_breach events), "
                         "drift (true forces divergence tracking — "
                         "loud when the artifact has no reference "
                         "histogram; false disables; default auto), "
                         "shadow_of=<name> (SHADOW mode: score the "
                         "named champion's traffic off the response "
                         "path — docs/SERVING.md). "
                         "Refs resolve through --registry (or are "
                         ".npz paths); duplicate names and unknown "
                         "refs fail loudly at boot")
    sv.add_argument("--fleet-config", default=None,
                    help="FLEET mode: JSON fleet config file "
                         "({\"models\": [{name, ref, weight, tier, "
                         "max_batch, raw, slo_p99_ms}, ...]}); combines with "
                         "--models (duplicate names across the two "
                         "fail loudly)")
    sv.add_argument("--max-resident", type=_positive_int, default=None,
                    help="fleet LRU budget: at most this many models "
                         "resident at once — cold models demote to "
                         "their AOT artifacts and reload zero-downtime "
                         "on next request (default: all resident)")
    sv.add_argument("--registry", default=None,
                    help="registry root directory (docs/REGISTRY.md): "
                         "resolve --model and /swap bodies as registry "
                         "references and serve through the zero-retrace "
                         "AOT loader — the model is deserialized, never "
                         "re-traced")
    sv.add_argument("--backend", choices=BACKENDS, default="tpu")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8199,
                    help="HTTP port (0 = ephemeral; printed on stdout)")
    sv.add_argument("--max-wait-ms", type=float, default=1.0,
                    help="admission window: how long a request may wait "
                         "for company before its micro-batch dispatches "
                         "(the latency/throughput knob)")
    sv.add_argument("--max-batch", type=_positive_int, default=256,
                    help="largest micro-batch (rows); batches pad to a "
                         "fixed power-of-two bucket ladder up to this, "
                         "so load never retraces")
    sv.add_argument("--quantized", nargs="?", const="int8", default=None,
                    choices=["int8", "int4"],
                    help="serve through the quantized TreeLUT ladder "
                         "(ops/predict_lut.py): bare flag = int8 tier, "
                         "'int4' = the bit-packed microsecond tier "
                         "(docs/SERVING.md quantization-tier table)")
    sv.add_argument("--raw", action="store_true",
                    help="return raw margins instead of probabilities")
    sv.add_argument("--no-express-lane", action="store_true",
                    help="disable the express lane (single-row "
                         "requests at an empty queue dispatch "
                         "immediately instead of waiting out the "
                         "admission window — on by default; "
                         "docs/SERVING.md)")
    sv.add_argument("--no-request-traces", action="store_true",
                    help="disable per-request trace propagation (the "
                         "X-DDT-Trace-Id/X-DDT-Timing response headers, "
                         "the /debug/requests ring, serve_trace "
                         "flushes) — on by default; a client-supplied "
                         "trace id is still echoed back "
                         "(docs/OBSERVABILITY.md)")
    sv.add_argument("--run-log", default=None,
                    help="JSONL run log for serve_latency SLO events "
                         "(render with `report` — docs/OBSERVABILITY.md)")

    rg = sub.add_parser(
        "registry",
        help="digest-addressed model registry (docs/REGISTRY.md): AOT-"
             "export servable artifacts, version them by name, restore "
             "them anywhere with zero retracing")
    rg.add_argument("--registry", required=True,
                    help="registry root directory (created on first push)")
    rgsub = rg.add_subparsers(dest="registry_cmd", required=True)
    rpu = rgsub.add_parser(
        "push", help="AOT-export a model artifact and publish it")
    rpu.add_argument("--model", required=True,
                     help="api.save_model .npz to export")
    rpu.add_argument("--name", default=None,
                     help="version the artifact under this name "
                          "(omit for an anonymous digest-only push)")
    rpu.add_argument("--tag", default=None,
                     help="also point this tag at the pushed version")
    rpu.add_argument("--max-batch", type=_positive_int, default=256,
                     help="largest serving micro-batch: the exported "
                          "pad-to-bucket ladder covers powers of two up "
                          "to this (must match the serving engine's)")
    rpu.add_argument("--quantize", nargs="?", const="int8", default=None,
                     choices=["int8", "int4"],
                     help="also export a quantized TreeLUT variant and "
                          "carry its tables in the artifact: bare flag "
                          "= the int8 tier, 'int4' = the bit-packed "
                          "tier (lut4 AOT blobs + int4 tables, "
                          "token-pinned round trip)")
    rpu.add_argument("--run-log", default=None,
                     help="append an `artifact` push event to this "
                          "JSONL run log (renders in `report`)")
    rls = rgsub.add_parser("list", help="inventory: names, versions, tags")
    rls.add_argument("--name", default=None,
                     help="limit to one model name")
    rls.add_argument("--json", action="store_true")
    rgt = rgsub.add_parser(
        "get", help="resolve + integrity-check a reference, print its "
                    "manifest")
    rgt.add_argument("ref", help="digest | name | name@version | name@tag")
    rtg = rgsub.add_parser("tag", help="point a tag at a version")
    rtg.add_argument("ref", help="name@version (or name for latest)")
    rtg.add_argument("tag", help="tag to set (non-numeric)")

    bp = sub.add_parser("bench", help="kernel/e2e benchmarks (JSON lines)")
    _add_common(bp)
    bp.add_argument("--kernel", default="histogram",
                    choices=["histogram", "train", "predict", "serve",
                             "registry", "hist_comms", "hist_2d",
                             "hist_quant", "lut4"])
    bp.add_argument("--grad-dtype", default=None,
                    choices=["int8", "int16"],
                    help="quantized arm for --kernel hist_quant "
                         "(default int8)")
    bp.add_argument("--features", type=int, default=None,
                    help="feature count; default = each kernel's own "
                         "(28 for the narrow arms, 1024 for the wide "
                         "hist_2d A/B)")
    bp.add_argument("--trees", type=int, default=100)
    bp.add_argument("--depth", type=int, default=6)
    bp.add_argument("--iters", type=int, default=10)
    bp.add_argument("--partitions", type=int, default=1)
    bp.add_argument("--hist-impl", default="auto")

    rp = sub.add_parser("report",
                        help="render a run summary from a JSONL telemetry "
                             "log (train --run-log), or diff two logs")
    rp.add_argument("--log", action="append",
                    help="path to the run log written by train --run-log; "
                         "repeat for a multi-host run's per-host logs "
                         "(merged by run id with manifest-estimated clock "
                         "offsets — docs/OBSERVABILITY.md)")
    rp.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object instead of "
                         "the human rendering")
    rp.add_argument("--slowest", type=_positive_int, default=5,
                    help="how many slowest rounds to list")
    rsub = rp.add_subparsers(dest="report_cmd")
    rsub.add_parser(
        "fleet",
        help="render the fleet rollup only: one row per model joining "
             "its serve_latency windows, serving tier, eviction/reload "
             "counts, and artifact provenance (docs/OBSERVABILITY.md); "
             "fails loudly on a log with no fleet data")
    rsub.add_parser(
        "slo",
        help="render the SLO rollup only: one row per model joining "
             "its declared p99 objective against the observed tail and "
             "the run's slo_breach burn rates (docs/OBSERVABILITY.md); "
             "fails loudly on a log with no SLO data")
    rsub.add_parser(
        "drift",
        help="render the drift rollup only: one row per model joining "
             "rolling-window feature divergence (PSI/JS against the "
             "training reference) with latched drift alerts, plus the "
             "champion/challenger shadow comparison "
             "(docs/OBSERVABILITY.md); fails loudly on a log with no "
             "drift data")
    rsub.add_parser(
        "progress",
        help="render the training-progress rollup only: round reached "
             "vs total, per-heartbeat pace (ms/round, rows/s) and the "
             "last checkpoint round, from the schema-v5 train_heartbeat "
             "events — built for logs of runs that DIED mid-round "
             "(heartbeats land at checkpoint cadence, so the tail "
             "survives a torn final line); fails loudly on a log with "
             "no heartbeat data (docs/OBSERVABILITY.md)")
    dp = rsub.add_parser(
        "diff",
        help="align two run logs by phase and counter and flag adverse "
             "excursions (benchwatch band logic, single-baseline form — "
             "docs/OBSERVABILITY.md)")
    dp.add_argument("log_a", help="baseline run log (A)")
    dp.add_argument("log_b", help="current run log (B)")
    dp.add_argument("--json", action="store_true",
                    help="emit the diff as one JSON object")
    dp.add_argument("--threshold", type=float, default=None,
                    help="adverse relative excursion that flags "
                         "(default 0.20 — benchwatch's relative floor)")
    dp.add_argument("--abs-floor-ms", type=float, default=None,
                    help="absolute per-phase floor below which moves "
                         "never flag (default 50 ms; 0 bands micro-runs)")
    dp.add_argument("--check", action="store_true",
                    help="exit 1 when any excursion is flagged (CI mode)")

    xp = sub.add_parser("trace",
                        help="export a run log as Chrome trace-event JSON "
                             "(open in ui.perfetto.dev): round slices, "
                             "per-partition lanes, instant markers")
    xp.add_argument("--log", required=True, action="append",
                    help="run-log JSONL path; repeat for per-host logs of "
                         "one pod run (merged before export)")
    xp.add_argument("--out", default="trace.json",
                    help="output trace path (default trace.json)")

    ip = sub.add_parser("inspect", help="summarize a saved ensemble")
    ip.add_argument("--model", required=True)
    ip.add_argument("--tree", type=int, default=None,
                    help="also print this tree's structure")
    ip.add_argument("--importance", choices=["split", "gain"],
                    default="gain")

    args = ap.parse_args(argv)

    if args.cmd == "train" and getattr(args, "fault_plan", None):
        # Arm the chaos plan process-wide BEFORE multihost bootstrap so
        # the multihost.init seam is injectable; the trainers see it
        # already active and leave it alone (docs/ROBUSTNESS.md).
        from ddt_tpu.robustness import faultplan

        try:
            faultplan.activate(faultplan.load_plan(args.fault_plan))
        except (OSError, ValueError) as e:
            raise SystemExit(f"--fault-plan: {e}") from e

    if args.cmd == "train" and (
            args.multihost_coordinator is not None
            or args.multihost_processes is not None):
        # Must run before ANY device use (SURVEY.md §5 "Distributed
        # communication backend": the v5e-64 pod bring-up).
        from ddt_tpu.parallel.mesh import initialize_multihost

        initialize_multihost(args.multihost_coordinator,
                             args.multihost_processes, args.multihost_id)

    if args.cmd == "train":
        file_cfg = None
        if args.config:
            from ddt_tpu.config import load_config_file

            try:
                file_cfg = load_config_file(args.config)
            except (OSError, ValueError) as e:
                raise SystemExit(f"--config: {e}") from e
            # Fields that feed DATASET loading / inference must apply
            # BEFORE the load, or the pipeline desynchronizes from the
            # training config (criteo encoder bins, label normalization
            # and n_classes inference via loss, generator/split seed,
            # reported backend). The full cfg cannot be built first:
            # cfg.n_classes is DISCOVERED by loading (softmax datasets),
            # so this list is the sync point — extend it if _load_dataset
            # ever reads another TrainConfig-backed value.
            for key, attr in (("n_bins", "bins"), ("seed", "seed"),
                              ("loss", "loss"), ("backend", "backend")):
                if key in file_cfg:
                    setattr(args, attr, file_cfg[key])
        if args.stream_dir:
            # Out-of-core path: nothing is loaded here — the shards stream
            # (softmax n_classes is discovered from the shard labels in
            # _train_streaming).
            X = y = encoder = None
            n_classes = 2
        else:
            X, y, n_classes, encoder = _load_dataset(args)
        loss = args.loss or (
            "softmax" if args.dataset == "covertype"
            else "mse" if args.dataset == "regression" else "logloss"
        )
        cat_features: tuple = ()
        if (args.dataset == "criteo" and args.cat_splits == "onehot"
                and not args.data
                and not args.stream_dir):   # --data overrides --dataset: its
            # columns are arbitrary, never implicitly categorical
            # The criteo layout (datasets.synthetic_ctr): 13 numeric
            # columns first, then the encoder's categorical columns.
            cat_features = tuple(range(13, X.shape[1]))
        cfg = TrainConfig(
            n_trees=args.trees, max_depth=args.depth, n_bins=args.bins,
            learning_rate=args.lr, loss=loss,
            n_classes=n_classes if loss == "softmax" else 2,
            backend=args.backend, n_partitions=args.partitions,
            feature_partitions=args.feature_partitions,
            host_partitions=args.host_partitions,
            mesh_shape=_parse_mesh_shape(args.mesh_shape),
            subsample=args.subsample,
            colsample_bytree=args.colsample_bytree,
            hist_impl=args.hist_impl, seed=args.seed,
            hist_subtraction=args.hist_subtraction,
            split_comms=args.split_comms,
            hist_comms_dtype=args.hist_comms_dtype,
            hist_comms_slabs=args.hist_comms_slabs,
            grad_dtype=args.grad_dtype,
            missing_policy=args.missing,
            cat_features=cat_features,
            fused_block_rounds=args.fused_block_rounds,
            fault_plan=args.fault_plan,
            straggler_repartition=args.straggler_repartition,
        )
        if file_cfg is not None:
            cfg = cfg.replace(**file_cfg)
        # Live training status daemon (telemetry/statusd.py). Lazy import
        # by design: without --status-port the statusd module is never
        # imported and no status object exists — the train loops' hooks
        # are all behind `is not None` (asserted in tests/test_statusd.py).
        status = daemon = None
        if args.status_port is not None:
            from ddt_tpu.telemetry.statusd import (TrainStatus,
                                                   start_statusd)

            status = TrainStatus()
            daemon = start_statusd(status, port=args.status_port)
            # Boot line FIRST (flushed): with --status-port=0 the kernel
            # picks the port, so scrapers read it from here.
            print(json.dumps({"statusd": {"host": daemon.host,
                                          "port": daemon.port}}),
                  flush=True)
        if args.stream_chunks > 0 or args.stream_dir:
            try:
                return _train_streaming(args, X, y, cfg, encoder,
                                        status=status)
            finally:
                if daemon is not None:
                    daemon.close()
        eval_set = None
        if args.valid_frac > 0:
            X, y, Xv, yv = _seeded_split(X, y, args.valid_frac, args.seed)
            eval_set = (Xv, yv)
        t0 = time.perf_counter()
        import contextlib

        trace_ctx = contextlib.nullcontext()
        if args.trace_dir:
            from ddt_tpu.utils.profiling import trace

            trace_ctx = trace(args.trace_dir)
        window = _capture_window(args)
        try:
            with trace_ctx:
                res = api.train(
                    X, y, cfg, checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every,
                    eval_set=eval_set, eval_metric=args.metric,
                    early_stopping_rounds=args.early_stop,
                    profile=args.profile,
                    run_log=args.run_log,
                    profiler_window=window,
                    status=status,
                )
        finally:
            # Daemon teardown is unconditional — a mid-fit death must not
            # leave the listener thread holding the port.
            if daemon is not None:
                daemon.close()
        dt = time.perf_counter() - t0
        # Persist the COMPLETE artifact: ensemble + training-time BinMapper
        # (+ CategoricalEncoder) so predict never refits preprocessing on
        # scoring data (round-1 verdict, Weak #2). The embedded manifest
        # carries the telemetry run_id + config fingerprint — the
        # provenance chain registry artifacts inherit (docs/REGISTRY.md).
        api.save_model(args.out, res.ensemble, mapper=res.mapper,
                       encoder=encoder, run_id=res.run_id, cfg=cfg)
        out = {
            "cmd": "train", "backend": args.backend, "rows": len(y),
            "trees": res.ensemble.n_trees, "depth": cfg.max_depth,
            "wallclock_s": round(dt, 3),
            "final_train_loss": next(
                (r["train_loss"] for r in reversed(res.history)
                 if r.get("train_loss") is not None), None),
            "model": args.out,
        }
        if res.best_score is not None:
            out["best_round"] = res.best_round + 1
            out["best_score"] = round(res.best_score, 6)
        if args.run_log:
            out["run_log"] = args.run_log
        if window is not None:
            out["xprof_dir"] = window.trace_dir
        print(json.dumps(out))
        return 0

    if args.cmd == "predict":
        bundle = api.load_model(args.model)
        ens = bundle.ensemble
        if args.stream_dir:
            return _predict_streaming(args, bundle)
        if args.dataset == "criteo" and not args.data \
                and bundle.encoder is None:
            # Same contract as the missing-mapper case below: refitting the
            # categorical encoder on scoring data silently mis-encodes.
            raise SystemExit(
                f"{args.model} carries no categorical encoder; retrain the "
                "criteo config with the current CLI (which saves it) — "
                "refusing to refit an encoder on scoring data."
            )
        X, y, _, _ = _load_dataset(args, encoder=bundle.encoder,
                                   n_features=ens.n_features)
        from ddt_tpu.serve.engine import TIER_IMPL

        cfg = TrainConfig(backend=args.backend, loss=ens.loss,
                          n_classes=max(ens.n_classes, 2),
                          n_partitions=max(1, args.partitions),
                          predict_impl=TIER_IMPL.get(args.quantized,
                                                     "auto"))
        t0 = time.perf_counter()
        if bundle.mapper is not None:
            # Training-time binning, loaded from the artifact — NEVER refit
            # on the scoring data (its distribution may differ).
            scores = api.predict(ens, X, mapper=bundle.mapper, cfg=cfg)
        elif ens.has_raw_thresholds:
            scores = api.predict(ens, X, cfg=cfg)  # raw-value traversal
        else:
            raise SystemExit(
                f"{args.model} carries neither a bin mapper nor raw "
                "thresholds; retrain with the current CLI (which saves the "
                "full artifact) or predict on pre-binned data via the API."
            )
        dt = time.perf_counter() - t0
        if args.out:
            np.save(args.out, scores)
        print(json.dumps({
            "cmd": "predict", "backend": args.backend, "rows": len(X),
            "trees": ens.n_trees, "wallclock_s": round(dt, 3),
            "rows_per_sec": round(len(X) / dt, 1),
        }))
        return 0

    if args.cmd == "serve":
        from ddt_tpu.serve.engine import TIER_IMPL, ServeEngine
        from ddt_tpu.serve.http import serve_forever

        if args.models or args.fleet_config:
            # FLEET mode (ISSUE 15): N registry-resolved models behind
            # one engine — parse/validate/resolve loudly at boot
            # (SystemExit-clean like the registry group), then serve.
            from ddt_tpu.registry import RegistryError
            from ddt_tpu.serve import control as fleet_control

            if args.model is not None:
                raise SystemExit(
                    "serve: --model conflicts with --models/"
                    "--fleet-config (put it in the fleet instead)")
            if args.quantized is not None or args.raw \
                    or args.max_batch != 256:
                # Silently dropping these would serve every model at
                # its default tier/ladder while the operator believes
                # otherwise — loud like the --model conflict above.
                raise SystemExit(
                    "serve: --quantized/--raw/--max-batch apply to "
                    "single-model servers; fleets set them per entry "
                    "(tier= / raw= / max_batch= in --models or the "
                    "fleet config)")
            try:
                specs = []
                if args.fleet_config:
                    specs += fleet_control.load_fleet_config(
                        args.fleet_config)
                if args.models:
                    specs += fleet_control.parse_models_arg(args.models)
                engine = fleet_control.build_fleet(
                    specs, registry=args.registry, backend=args.backend,
                    max_wait_ms=args.max_wait_ms,
                    max_resident=args.max_resident,
                    run_log=args.run_log,
                    express_lane=not args.no_express_lane,
                    request_traces=not args.no_request_traces)
            except (fleet_control.FleetConfigError, RegistryError,
                    ValueError, OSError) as e:
                raise SystemExit(f"serve fleet: {e}") from e
            print(json.dumps({
                "cmd": "serve", "fleet": True,
                "models": {s.name: {"ref": s.ref, "weight": s.weight,
                                    "tier": s.tier,
                                    "max_batch": s.max_batch}
                           for s in specs},
                "max_resident": args.max_resident,
                "host": args.host, "port": args.port,
                "max_wait_ms": args.max_wait_ms,
                "express_lane": not args.no_express_lane,
                "registry": args.registry,
            }), flush=True)
            serve_forever(engine, host=args.host, port=args.port)
            return 0

        if args.model is None:
            raise SystemExit(
                "serve: --model is required (or configure a fleet "
                "with --models/--fleet-config)")

        mode = "file"
        digest = None
        if args.registry is not None and not os.path.exists(args.model):
            # Registry serving: restore through the zero-retrace loader
            # — the artifact's AOT programs deserialize here, the model
            # is never re-traced in this process, and the engine's
            # bucket ladder is the ARTIFACT's (the shapes that were
            # exported are exactly the shapes that serve).
            from ddt_tpu.registry import RegistryError
            from ddt_tpu.registry import loader as reg_loader
            from ddt_tpu.telemetry.events import RunLog

            # ONE RunLog for the whole serve lifetime: the loader's
            # boot-time artifact event and the engine's serving events
            # share the handle and the per-log monotonic seq (merge's
            # tie-break invariant); the engine closes it at shutdown.
            run_log = RunLog.coerce(args.run_log)
            try:
                report = reg_loader.load_servable(
                    args.registry, args.model,
                    # Flag absent (None) = the engine serves f32 even
                    # from a quantized artifact (the engine's mode
                    # wins) — None would FOLLOW the artifact instead.
                    quantize=args.quantized or False,
                    raw=args.raw, backend=args.backend,
                    run_log=run_log)
            except (RegistryError, ValueError, OSError) as e:
                raise SystemExit(f"serve --registry: {e}") from e
            servable = report.model
            mode, digest = report.mode, report.digest
            cfg = TrainConfig(
                backend=args.backend, loss=servable.ens.loss,
                n_classes=max(servable.ens.n_classes, 2),
                predict_impl=TIER_IMPL.get(args.quantized, "auto"))
            engine = ServeEngine(
                servable, cfg, max_wait_ms=args.max_wait_ms,
                max_batch=servable.buckets[-1], quantize=args.quantized,
                raw=args.raw, run_log=run_log,
                express_lane=not args.no_express_lane,
                request_traces=not args.no_request_traces)
        else:
            bundle = api.load_model(args.model)
            cfg = TrainConfig(
                backend=args.backend, loss=bundle.ensemble.loss,
                n_classes=max(bundle.ensemble.n_classes, 2),
                predict_impl=TIER_IMPL.get(args.quantized, "auto"))
            engine = ServeEngine(
                bundle, cfg, max_wait_ms=args.max_wait_ms,
                max_batch=args.max_batch, quantize=args.quantized,
                raw=args.raw, run_log=args.run_log,
                express_lane=not args.no_express_lane,
                request_traces=not args.no_request_traces)
        engine.registry_root = args.registry
        print(json.dumps({
            "cmd": "serve", "model": args.model,
            "model_token": engine.model_token,
            "quantized": args.quantized, "host": args.host,
            "port": args.port, "max_wait_ms": args.max_wait_ms,
            "max_batch": engine.buckets[-1],
            "express_lane": not args.no_express_lane,
            "registry": args.registry, "mode": mode,
            "artifact_digest": digest,
        }), flush=True)
        serve_forever(engine, host=args.host, port=args.port)
        return 0

    if args.cmd == "registry":
        from ddt_tpu.registry import IntegrityError, Registry, RegistryError

        reg = Registry(args.registry)
        try:
            if args.registry_cmd == "push":
                from ddt_tpu.registry.loader import push_servable

                bundle = api.load_model(args.model)
                out = push_servable(
                    reg, bundle, name=args.name, tag=args.tag,
                    max_batch=args.max_batch, quantize=args.quantize,
                    run_log=args.run_log)
                print(json.dumps({"cmd": "registry_push",
                                  "model": args.model, **out}))
                return 0
            if args.registry_cmd == "list":
                inv = reg.list(name=args.name)
                if args.json:
                    print(json.dumps(inv))
                else:
                    for name, idx in sorted(inv["names"].items()):
                        tags = {t: v for t, v in idx["tags"].items()}
                        for v in idx["versions"]:
                            vt = [t for t, tv in tags.items()
                                  if tv == v["version"]]
                            print(f"{name}@{v['version']}  {v['digest']}"
                                  + (f"  run_id={v['run_id']}"
                                     if v.get("run_id") else "")
                                  + ("  quantized" if v.get("quantized")
                                     else "")
                                  + (f"  [{', '.join(vt)}]" if vt else ""))
                    for d in inv["anonymous"]:
                        print(f"(anonymous)  {d}")
                return 0
            if args.registry_cmd == "get":
                art_dir, man, digest = reg.get(args.ref)
                print(json.dumps({
                    "cmd": "registry_get", "ref": args.ref,
                    "digest": digest, "path": art_dir,
                    "manifest": {k: v for k, v in man.items()
                                 if k != "files"},
                    "n_files": len(man["files"]),
                }))
                return 0
            if args.registry_cmd == "tag":
                print(json.dumps({"cmd": "registry_tag",
                                  **reg.tag(args.ref, args.tag)}))
                return 0
        except (RegistryError, IntegrityError, OSError) as e:
            raise SystemExit(f"registry {args.registry_cmd}: {e}") from e
        return 2  # pragma: no cover

    if args.cmd == "report":
        from ddt_tpu.telemetry import merge as tele_merge
        from ddt_tpu.telemetry import report as tele_report

        if getattr(args, "report_cmd", None) == "diff":
            from ddt_tpu.telemetry import diffing

            try:
                sa = tele_report.summarize(
                    tele_report.read_events(args.log_a))
                sb = tele_report.summarize(
                    tele_report.read_events(args.log_b))
                kw = {}
                if args.threshold is not None:
                    kw["threshold"] = args.threshold
                if args.abs_floor_ms is not None:
                    kw["abs_floor_ms"] = args.abs_floor_ms
                d = diffing.diff_summaries(sa, sb, **kw)
                out_text = (json.dumps(d) if args.json
                            else diffing.render_diff(d, args.log_a,
                                                     args.log_b))
            except (OSError, ValueError, TypeError, KeyError) as e:
                raise SystemExit(f"report diff: {e}") from e
            print(out_text)
            return 1 if (args.check and d["flagged"]) else 0

        if not args.log:
            ap.error("report requires --log (or the `diff A B` form)")
        try:
            events = tele_merge.merge_paths(args.log)
            summary = tele_report.summarize(events, slowest=args.slowest)
            if getattr(args, "report_cmd", None) == "fleet":
                # `report --log L fleet`: just the per-model rollup
                # (render_fleet raises on a log with no fleet data —
                # caught below into the clean SystemExit; the --json
                # form validates through it too).
                out_text = tele_report.render_fleet(summary)
                if args.json:
                    out_text = json.dumps(summary["fleet"])
            elif getattr(args, "report_cmd", None) == "slo":
                # `report --log L slo`: just the SLO rollup (render_slo
                # raises on a log with no SLO data — caught below into
                # the clean SystemExit, same shape as `fleet`).
                out_text = tele_report.render_slo(summary)
                if args.json:
                    out_text = json.dumps(summary["slo"])
            elif getattr(args, "report_cmd", None) == "drift":
                # `report --log L drift`: just the drift rollup
                # (render_drift raises on a log with no drift signal —
                # caught below into the clean SystemExit, same shape
                # as `fleet`/`slo`).
                out_text = tele_report.render_drift(summary)
                if args.json:
                    out_text = json.dumps(summary["drift"])
            elif getattr(args, "report_cmd", None) == "progress":
                # `report --log L progress`: how far a (possibly dead)
                # run got — heartbeat-round table + pace + last
                # checkpoint (render_progress raises on a log with no
                # train_heartbeat events — caught below into the clean
                # SystemExit, same shape as `fleet`/`slo`/`drift`).
                out_text = tele_report.render_progress(summary)
                if args.json:
                    out_text = json.dumps(summary["progress"])
            else:
                out_text = (json.dumps(summary) if args.json
                            else tele_report.render(summary))
        except (OSError, ValueError, TypeError, KeyError) as e:
            # summarize/render stay inside the guard: a schema-valid log
            # with wrong field TYPES (hand-edited/corrupted) must exit
            # with the clean message, not a raw traceback.
            raise SystemExit(f"report: {e}") from e
        print(out_text)
        return 0

    if args.cmd == "trace":
        from ddt_tpu.telemetry import merge as tele_merge
        from ddt_tpu.telemetry import perfetto as tele_perfetto

        try:
            events = tele_merge.merge_paths(args.log)
            n = tele_perfetto.write_trace(events, args.out)
        except (OSError, ValueError, TypeError, KeyError) as e:
            raise SystemExit(f"trace: {e}") from e
        print(json.dumps({
            "cmd": "trace", "logs": args.log, "events": len(events),
            "trace_events": n, "out": args.out,
        }))
        return 0

    if args.cmd == "bench":
        from ddt_tpu.bench import run_bench

        out = run_bench(
            kernel=args.kernel, backend=args.backend, rows=args.rows,
            features=args.features, bins=args.bins, trees=args.trees,
            depth=args.depth, iters=args.iters, partitions=args.partitions,
            hist_impl=args.hist_impl, seed=args.seed,
            grad_dtype=args.grad_dtype,
        )
        print(json.dumps(out))
        return 0

    if args.cmd == "inspect":
        ens = TreeEnsemble.load(args.model)
        if args.tree is not None and not (0 <= args.tree < ens.n_trees):
            ap.error(f"--tree must be in [0, {ens.n_trees}), got {args.tree}")
        imp = ens.feature_importances(kind=args.importance)
        if args.importance == "gain" and not imp.any():
            # Pre-gain archive (split_gain backfilled with zeros): fall back
            # so legacy models remain inspectable, and say so.
            print("# no recorded gains (model predates gain recording); "
                  "showing split-count importance", file=sys.stderr)
            args.importance = "split"
            imp = ens.feature_importances(kind="split")
        top = np.argsort(imp)[::-1][:10]
        print(json.dumps({
            "cmd": "inspect", "model": args.model,
            "n_trees": ens.n_trees, "max_depth": ens.max_depth,
            "n_features": ens.n_features, "loss": ens.loss,
            "n_classes": ens.n_classes,
            "learning_rate": ens.learning_rate,
            "base_score": ens.base_score,
            "n_splits": int(((~ens.is_leaf) & (ens.feature >= 0)).sum()),
            "has_raw_thresholds": bool(ens.has_raw_thresholds),
            f"top_features_by_{args.importance}": {
                int(f): round(float(imp[f]), 5) for f in top if imp[f] > 0
            },
        }))
        if args.tree is not None:
            print(ens.dump_text(args.tree))
        return 0

    return 2  # pragma: no cover


if __name__ == "__main__":
    sys.exit(main())
