"""Streaming trainer for datasets that don't fit in device (or host) memory.

The 10B-row / 1024-feature stress config (BASELINE.json) cannot hold a binned
matrix anywhere — 10 TB of uint8. SURVEY.md §5's "long axis" story: shard and
STREAM the row axis with per-chunk histogram accumulation. Histograms are
small (≤ MBs) and additive, so streaming needs no ring algorithms: per level,

    hist = Σ_chunks build_histograms(chunk, g_chunk, h_chunk, node_of_row)

with node_of_row recomputed per chunk by STATELESS traversal of the partial
tree — a row's node at level d is fully determined by the tree grown so far,
so no per-row state survives between chunks. Gradients are likewise stateless:
pred of a row is the partial ensemble's score (optionally cached per chunk on
host when it fits — cache_preds trades O(T²) rescoring for O(R) host RAM).

The chunk source is a callable (chunk_idx) -> (Xb_chunk, y_chunk): pure, so
any chunk can be regenerated on any host at any time (the deterministic
synthetic generator data/datasets.stress_binned_chunk is one; a file-backed
loader fits the same signature). Chunks may differ in size (each distinct
size jit-compiles its own per-level program — keep the number of distinct
sizes small); empty chunks are not allowed. This trainer produces
BIT-IDENTICAL trees to the in-memory Driver on the same data
(tests/test_streaming.py) — the chunk sum enters the same bf16-rounded
split selection (ops/split.py).

Distribution composes: each chunk is row-sharded over the TPUDevice mesh like
any other upload, so a v5e-64 pod streams 8 host-chunks in parallel while each
chunk's histogram psum rides ICI (SURVEY.md §7 M6).
"""

from __future__ import annotations

import logging
from typing import Callable

import numpy as np

from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble, empty_ensemble
from ddt_tpu.reference.numpy_trainer import grad_hess
from ddt_tpu.utils import checkpoint

log = logging.getLogger("ddt_tpu.streaming")

ChunkFn = Callable[[int], tuple[np.ndarray, np.ndarray]]


def validate_mapper_config(mapper, cfg: TrainConfig) -> None:
    """The mapper↔config consistency guards api.train enforces, for the
    streaming paths (a mismatched mapper trains a silently wrong model,
    not a crashing one)."""
    if mapper.n_bins != cfg.n_bins:
        raise ValueError(
            f"mapper was fitted with n_bins={mapper.n_bins} but "
            f"cfg.n_bins={cfg.n_bins}"
        )
    if (cfg.missing_policy == "learn") != mapper.missing_bin:
        raise ValueError(
            f"mapper.missing_bin={mapper.missing_bin} but "
            f"cfg.missing_policy={cfg.missing_policy!r}; refit the mapper "
            "with the same policy"
        )
    if cfg.cat_features:
        bad = mapper.non_identity_columns(cfg.cat_features)
        if bad:
            raise ValueError(
                f"cat_features {bad} were not identity-binned by this "
                "mapper; refit it with "
                f"cat_features={tuple(sorted(cfg.cat_features))}"
            )


def binned_chunks(chunk_fn: ChunkFn, mapper, cfg: TrainConfig) -> ChunkFn:
    """Adapt a RAW-float chunk source into the binned source
    fit_streaming consumes, via a fitted BinMapper (see
    data/quantizer.fit_bin_mapper_streaming for fitting one without
    materialising the dataset). Purity is preserved: any chunk still
    regenerates anywhere, bins included — which also means every re-read
    re-bins; callers whose binned chunks fit somewhere can cache them.

    `cfg` is required so the mapper↔config consistency guards that
    api.train enforces hold on this path too."""
    validate_mapper_config(mapper, cfg)

    def f(c: int):
        X, y = chunk_fn(c)
        return mapper.transform(np.asarray(X, np.float32)), y

    # Side-channel accessors so fit_streaming's label-only pass 0 and
    # shape probe skip the (expensive) binning of chunks they would
    # otherwise transform and throw away.
    f.labels = lambda c: chunk_fn(c)[1]
    f.n_features = mapper.n_features
    return f


def _go_right(
    fv: np.ndarray,           # winning-column bin values for the live rows
    nodes: np.ndarray,        # their heap slots
    feature: np.ndarray,
    threshold_bin: np.ndarray,
    default_left: np.ndarray | None,
    missing_bin_value: int,
    cat_features: tuple,
) -> np.ndarray:
    """Routing decision with the full split semantics (ordinal,
    categorical one-vs-rest, reserved-NaN-bin default direction) — the
    single host home of the streamed routing rule."""
    thr = threshold_bin[nodes]
    go_right = fv > thr
    if cat_features:
        cat = np.isin(feature[nodes], cat_features)
        go_right = np.where(cat, fv != thr, go_right)
    if missing_bin_value >= 0:
        go_right = np.where(fv == missing_bin_value,
                            ~default_left[nodes], go_right)
    return go_right


def _traverse_partial(
    Xb: np.ndarray,
    feature: np.ndarray,
    threshold_bin: np.ndarray,
    is_leaf: np.ndarray,
    depth: int,
    default_left: np.ndarray | None = None,
    missing_bin_value: int = -1,
    cat_features: tuple = (),
) -> np.ndarray:
    """Stateless node assignment at `depth`: heap slot per row, or -1 when the
    row froze at a leaf above this level. Mirrors the in-memory grow loop's
    (node_id, frozen) evolution exactly."""
    R = Xb.shape[0]
    node = np.zeros(R, np.int64)
    frozen = np.zeros(R, bool)
    for d in range(depth):
        live = ~frozen & ~is_leaf[node]
        frozen |= is_leaf[node]
        f = feature[node[live]]
        fv = Xb[live, f].astype(np.int64)
        go_right = _go_right(fv, node[live], feature, threshold_bin,
                             default_left, missing_bin_value, cat_features)
        node[live] = 2 * node[live] + 1 + go_right
    offset = (1 << depth) - 1
    out = (node - offset).astype(np.int32)
    out[frozen] = -1
    return out


def _apply_level_splits(
    hist: np.ndarray,
    cfg: TrainConfig,
    depth: int,
    feature: np.ndarray,
    threshold_bin: np.ndarray,
    is_leaf: np.ndarray,
    leaf_value: np.ndarray,
    split_gain: np.ndarray,
    default_left: np.ndarray | None = None,
) -> None:
    """Level-`depth` split decisions from the accumulated histogram,
    written into the node arrays in place. The SINGLE home of the
    streamed split rule — both the host and device loops call this, so
    host/device bit-identity cannot drift."""
    from ddt_tpu.reference.numpy_trainer import best_splits, node_totals

    n_level = 1 << depth
    offset = n_level - 1
    G, H = node_totals(hist)
    cat_mask = None
    if cfg.cat_features:
        cat_mask = np.zeros(hist.shape[1], bool)
        cat_mask[list(cfg.cat_features)] = True
    gains, feats, bins, dls = best_splits(
        hist, cfg.reg_lambda, cfg.min_child_weight,
        missing_bin=cfg.missing_policy == "learn", cat_mask=cat_mask)
    with np.errstate(divide="ignore", invalid="ignore"):   # empty nodes
        value = np.where(H > 0, -G / (H + cfg.reg_lambda), 0.0).astype(
            np.float32)
    do_split = (gains > cfg.min_split_gain) & np.isfinite(gains) & (H > 0)
    for i in range(n_level):
        slot = offset + i
        if do_split[i]:
            feature[slot] = feats[i]
            threshold_bin[slot] = bins[i]
            split_gain[slot] = gains[i]
            if default_left is not None:
                default_left[slot] = dls[i]
        else:
            is_leaf[slot] = True
            leaf_value[slot] = value[i]


def _apply_final_leaves(
    Gl: np.ndarray,
    Hl: np.ndarray,
    cfg: TrainConfig,
    is_leaf: np.ndarray,
    leaf_value: np.ndarray,
) -> None:
    """Final-level leaf values from streamed (G, H) aggregates (shared by
    the host and device loops)."""
    n_last = 1 << cfg.max_depth
    offset = n_last - 1
    with np.errstate(divide="ignore", invalid="ignore"):   # empty nodes
        vals = np.where(Hl > 0, -Gl / (Hl + cfg.reg_lambda), 0.0)
    is_leaf[offset:offset + n_last] = True
    leaf_value[offset:offset + n_last] = vals.astype(np.float32)


def fit_streaming(
    chunk_fn: ChunkFn,
    n_chunks: int,
    cfg: TrainConfig,
    backend=None,
    cache_preds: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 25,
) -> TreeEnsemble:
    """Train a GBDT over `n_chunks` streamed chunks.

    Device backends exposing the stream_* surface (TPUDevice) run the
    whole per-(chunk, level) step on device — traversal, grads, histogram,
    psum — with the NEXT chunk's upload overlapping the current chunk's
    compute, and per-chunk boosting state (pred, labels) resident on
    device for the whole run (ops/stream.py; supports softmax and
    n_partitions/host_partitions > 1). Host backends stream the original
    host formulation (binary/mse). Both are bit-identical to the in-memory
    Driver on the same data, including missing_policy='learn' (reserved
    NaN bin + learned default directions) and categorical one-vs-rest
    splits (tests/test_streaming.py).
    """
    if backend is None:
        from ddt_tpu.backends import get_backend

        backend = get_backend(cfg)

    device = hasattr(backend, "stream_level_hist")
    if cfg.loss == "softmax" and not device:
        raise NotImplementedError(
            "host-path streaming softmax is not wired; use the TPU "
            "backend (device streaming supports softmax)"
        )

    # Pass 0: base score from running label sums + shape discovery — no
    # O(R) host state anywhere in this trainer except the optional preds
    # cache (see below); at the 10B-row target everything else is O(chunk).
    # Device backends also ship labels NOW (one read of each chunk, not a
    # second pass): labels stay device-resident for the whole run.
    y_sum, y_cnt = 0.0, 0
    chunk_lens = []
    y_dev = []
    # binned_chunks-style adapters expose a label-only accessor so this
    # pass doesn't pay for binning feature matrices it never reads.
    labels_of = getattr(chunk_fn, "labels", None) or (
        lambda c: chunk_fn(c)[1])
    for c in range(n_chunks):
        yc = labels_of(c)
        if len(yc) == 0:
            # Fail HERE, at the cause — a zero-row chunk otherwise dies
            # far away (device shard padding / NaN base score).
            raise ValueError(
                f"chunk {c} is empty; empty chunks are not allowed "
                "(re-cut the chunk boundaries)"
            )
        y_sum += float(np.sum(yc))
        y_cnt += len(yc)
        chunk_lens.append(len(yc))
        if device:
            y_dev.append(backend.upload_labels(np.asarray(yc)))
    mean = y_sum / max(1, y_cnt)
    if cfg.loss == "logloss":
        p_ = float(np.clip(mean, 1e-6, 1 - 1e-6))
        bs = float(np.log(p_ / (1 - p_)))
    elif cfg.loss == "softmax":
        bs = 0.0
    else:
        bs = float(mean)
    F = getattr(chunk_fn, "n_features", None)
    if F is None:
        F = chunk_fn(0)[0].shape[1]

    C = cfg.n_classes if cfg.loss == "softmax" else 1
    ens = empty_ensemble(
        cfg.n_trees * C, cfg.max_depth, F, cfg.learning_rate, bs,
        cfg.loss, cfg.n_classes,
        missing_bin=cfg.missing_policy == "learn", n_bins=cfg.n_bins,
        cat_features=cfg.cat_features,
    )
    # Checkpoint/resume (SURVEY.md §5) — the streamed runs are the LONGEST
    # ones, so restartability matters most here. Boosting state is
    # reconstituted by rescoring the restored partial ensemble per chunk
    # with the Driver's per-round accumulation order (bit-exact resume).
    start_round = 0
    if checkpoint_dir is not None:
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        from ddt_tpu.utils.checkpoint import try_resume

        start_round = try_resume(checkpoint_dir, ens, cfg)
        if start_round > 0:
            log.info("streaming: resumed from checkpoint at round %d",
                     start_round)
        if start_round >= cfg.n_trees:
            # Already finished (e.g. a preemptible-restart loop re-runs
            # the command): return the restored ensemble without the full
            # boosting-state reconstitution pass over the dataset.
            return ens

    if device:
        return _fit_streaming_device(
            chunk_fn, n_chunks, cfg, backend, ens, bs, C, y_dev,
            start_round=start_round, checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every)

    # The ONE optional O(R) structure: per-chunk cached raw scores (4 bytes/
    # row). cache_preds=False recomputes scores from the partial ensemble
    # instead (O(T) traversals per row per round) — choose by host RAM.
    preds = (
        [np.full(chunk_lens[c], bs, np.float32) for c in range(n_chunks)]
        if cache_preds else None
    )
    if preds is not None and start_round > 0:
        part = ens.truncate(start_round)
        for c in range(n_chunks):
            preds[c] = part.predict_raw_roundwise(
                chunk_fn(c)[0], binned=True).astype(np.float32)

    missing_val = cfg.missing_bin_value
    for t in range(start_round, cfg.n_trees):
        # Grow one tree level-by-level; histograms accumulate across chunks.
        feature = np.full(cfg.n_nodes_total, -1, np.int32)
        threshold_bin = np.zeros(cfg.n_nodes_total, np.int32)
        is_leaf = np.zeros(cfg.n_nodes_total, bool)
        leaf_value = np.zeros(cfg.n_nodes_total, np.float32)
        split_gain = np.zeros(cfg.n_nodes_total, np.float32)
        default_left = np.zeros(cfg.n_nodes_total, bool)

        def chunk_grads(c: int, Xc, yc):
            pred_c = preds[c] if preds is not None else _rescore(
                ens, t, Xc, bs
            )
            return grad_hess(pred_c, np.asarray(yc), cfg.loss)

        route_kw = dict(default_left=default_left,
                        missing_bin_value=missing_val,
                        cat_features=cfg.cat_features)
        for depth in range(cfg.max_depth):
            n_level = 1 << depth
            offset = n_level - 1
            hist = None
            for c in range(n_chunks):
                Xc, yc = chunk_fn(c)
                ni = _traverse_partial(
                    Xc, feature, threshold_bin, is_leaf, depth, **route_kw
                )
                g, h = chunk_grads(c, Xc, yc)
                data = backend.upload(Xc)
                part = np.asarray(
                    backend.build_histograms(data, g, h, ni, n_level)
                )
                hist = part if hist is None else hist + part
            _apply_level_splits(hist, cfg, depth, feature, threshold_bin,
                                is_leaf, leaf_value, split_gain,
                                default_left)

        # Final level: per-terminal (G, H) aggregates streamed the same way.
        n_last = 1 << cfg.max_depth
        Gl = np.zeros(n_last, np.float32)
        Hl = np.zeros(n_last, np.float32)
        for c in range(n_chunks):
            Xc, yc = chunk_fn(c)
            ni = _traverse_partial(
                Xc, feature, threshold_bin, is_leaf, cfg.max_depth,
                **route_kw
            )
            g, h = chunk_grads(c, Xc, yc)
            act = ni >= 0
            np.add.at(Gl, ni[act], g[act])
            np.add.at(Hl, ni[act], h[act])
        _apply_final_leaves(Gl, Hl, cfg, is_leaf, leaf_value)

        ens.feature[t] = feature
        ens.threshold_bin[t] = threshold_bin
        ens.is_leaf[t] = is_leaf
        ens.leaf_value[t] = leaf_value
        ens.split_gain[t] = split_gain
        if ens.default_left is not None:
            ens.default_left[t] = default_left

        if preds is not None:
            # leaf slot per row = heap slot where traversal stopped: either
            # offset+ni (made it to the last level) or the frozen leaf —
            # rescore via the tree to keep it simple and exact.
            for c in range(n_chunks):
                Xc, _ = chunk_fn(c)
                slot = _leaf_slot(
                    Xc, feature, threshold_bin, is_leaf, cfg.max_depth,
                    **route_kw
                )
                preds[c] += cfg.learning_rate * leaf_value[slot]

        log.info("streaming: tree %d/%d done", t + 1, cfg.n_trees)
        checkpoint.maybe_save(checkpoint_dir, ens, cfg, t + 1,
                              checkpoint_every)

    checkpoint.maybe_save(checkpoint_dir, ens, cfg, cfg.n_trees)
    return ens


def _fit_streaming_device(
    chunk_fn: ChunkFn,
    n_chunks: int,
    cfg: TrainConfig,
    backend,
    ens: TreeEnsemble,
    bs: float,
    C: int,
    y_dev: list,
    start_round: int = 0,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 25,
) -> TreeEnsemble:
    """Device streaming loop: see fit_streaming. Per tree it makes
    max_depth histogram passes + 1 leaf pass (+ 1 pred-update pass between
    rounds) over the chunks; each pass re-uploads only Xb (uint8 —
    pred/labels stay device-resident), and the next chunk's host read +
    H2D upload is enqueued BEFORE the current chunk's small output is
    fetched, so the transfer rides under the device compute (double
    buffering via JAX's async dispatch)."""
    # Device-resident per-chunk boosting state (labels were shipped during
    # pass 0): pred for the whole run — 4C bytes/row, row-sharded over the
    # mesh like the data, per-chip tiny next to the streamed Xb.
    pred_dev = [backend.init_pred(h, bs) for h in y_dev]
    if start_round > 0:
        # Resume: REPLAY the identical device update ops over the restored
        # trees (rounds ascending, classes ascending — the training
        # order). Host rescoring would differ by FMA-contraction ULPs
        # (XLA fuses pred + lr*dv into one rounding); replaying the same
        # compiled op is bit-exact vs an uninterrupted run by
        # construction. One upload pass over the chunks, start_round*C
        # cheap update dispatches each.
        for c in range(n_chunks):
            data = backend.upload(chunk_fn(c)[0])
            for r in range(start_round):
                for cls in range(C):
                    slot = r * C + cls
                    tree_full = (
                        ens.feature[slot], ens.threshold_bin[slot],
                        ens.is_leaf[slot], ens.leaf_value[slot],
                        ens.default_left[slot],
                    )
                    pred_dev[c] = backend.stream_update_pred(
                        data, pred_dev[c], tree_full, cfg.max_depth, cls)

    def passes(tree, depth, kind, class_idx):
        """One full pass over the chunks; yields per-chunk device outputs
        with the next upload already in flight."""
        data = backend.upload(chunk_fn(0)[0])
        for c in range(n_chunks):
            if kind == "hist":
                out = backend.stream_level_hist(
                    data, pred_dev[c], y_dev[c], tree, depth, class_idx)
            else:
                out = backend.stream_leaf_gh(
                    data, pred_dev[c], y_dev[c], tree, depth, class_idx)
            if c + 1 < n_chunks:        # prefetch: overlap H2D with compute
                data = backend.upload(chunk_fn(c + 1)[0])
            yield np.asarray(out)       # fetch (device likely done by now)

    t_out = start_round * C
    for rnd in range(start_round, cfg.n_trees):
        # Gradients for EVERY class tree of a round come from the
        # round-start preds (the Driver computes grad_hess once per round,
        # then grows C trees from its columns) — so pred updates are
        # deferred to one pass after all classes (which also costs one
        # data pass per round instead of C).
        round_trees = []
        for cls in range(C):
            feature = np.full(cfg.n_nodes_total, -1, np.int32)
            threshold_bin = np.zeros(cfg.n_nodes_total, np.int32)
            is_leaf = np.zeros(cfg.n_nodes_total, bool)
            leaf_value = np.zeros(cfg.n_nodes_total, np.float32)
            split_gain = np.zeros(cfg.n_nodes_total, np.float32)
            default_left = np.zeros(cfg.n_nodes_total, bool)
            tree = (feature, threshold_bin, is_leaf, default_left)

            for depth in range(cfg.max_depth):
                hist = None
                for part in passes(tree, depth, "hist", cls):
                    hist = part if hist is None else hist + part
                _apply_level_splits(hist, cfg, depth, feature,
                                    threshold_bin, is_leaf, leaf_value,
                                    split_gain, default_left)

            # Final level: streamed (G, H) aggregates.
            GH = None
            for part in passes(tree, cfg.max_depth, "leaf", cls):
                GH = part if GH is None else GH + part
            _apply_final_leaves(GH[:, 0], GH[:, 1], cfg, is_leaf,
                                leaf_value)

            round_trees.append(
                (feature, threshold_bin, is_leaf, leaf_value,
                 default_left))
            ens.feature[t_out] = feature
            ens.threshold_bin[t_out] = threshold_bin
            ens.is_leaf[t_out] = is_leaf
            ens.leaf_value[t_out] = leaf_value
            ens.split_gain[t_out] = split_gain
            if ens.default_left is not None:
                ens.default_left[t_out] = default_left
            t_out += 1

        # One update pass: apply all of the round's class trees to the
        # device-resident preds (independent columns). Preds are only read
        # by the NEXT round's gradient passes, so the final round skips
        # the pass entirely (a whole dataset re-read on the transfer-bound
        # path).
        if rnd + 1 < cfg.n_trees:
            data = backend.upload(chunk_fn(0)[0])
            for c in range(n_chunks):
                for cls, tree_full in enumerate(round_trees):
                    pred_dev[c] = backend.stream_update_pred(
                        data, pred_dev[c], tree_full, cfg.max_depth, cls)
                if c + 1 < n_chunks:
                    data = backend.upload(chunk_fn(c + 1)[0])
        log.info("streaming: round %d/%d done", rnd + 1, cfg.n_trees)
        checkpoint.maybe_save(checkpoint_dir, ens, cfg, rnd + 1,
                              checkpoint_every)

    checkpoint.maybe_save(checkpoint_dir, ens, cfg, cfg.n_trees)
    return ens


def _leaf_slot(Xb, feature, threshold_bin, is_leaf, max_depth,
               default_left=None, missing_bin_value=-1,
               cat_features=()) -> np.ndarray:
    """Heap slot where each row's traversal of one tree terminates."""
    R = Xb.shape[0]
    node = np.zeros(R, np.int64)
    for _ in range(max_depth):
        live = ~is_leaf[node]
        f = feature[node[live]]
        fv = Xb[live, f].astype(np.int64)
        go_right = _go_right(fv, node[live], feature, threshold_bin,
                             default_left, missing_bin_value, cat_features)
        node[live] = 2 * node[live] + 1 + go_right
    return node


def _rescore(ens: TreeEnsemble, n_trees_done: int, Xb, bs) -> np.ndarray:
    """Stateless pred of the first n_trees_done trees (cache_preds=False)."""
    if n_trees_done == 0:
        return np.full(Xb.shape[0], bs, np.float32)
    return ens.truncate(n_trees_done).predict_raw(
        Xb, binned=True).astype(np.float32)
