"""Streaming trainer for datasets that don't fit in device (or host) memory.

The 10B-row / 1024-feature stress config (BASELINE.json) cannot hold a binned
matrix anywhere — 10 TB of uint8. SURVEY.md §5's "long axis" story: shard and
STREAM the row axis with per-chunk histogram accumulation. Histograms are
small (≤ MBs) and additive, so streaming needs no ring algorithms: per level,

    hist = Σ_chunks build_histograms(chunk, g_chunk, h_chunk, node_of_row)

with node_of_row recomputed per chunk by STATELESS traversal of the partial
tree — a row's node at level d is fully determined by the tree grown so far,
so no per-row state survives between chunks. Gradients are likewise stateless:
pred of a row is the partial ensemble's score (optionally cached per chunk on
host when it fits — cache_preds trades O(T²) rescoring for O(R) host RAM).

The chunk source is a callable (chunk_idx) -> (Xb_chunk, y_chunk): pure, so
any chunk can be regenerated on any host at any time (the deterministic
synthetic generator data/datasets.stress_binned_chunk is one; a file-backed
loader fits the same signature). Chunks may differ in size (each distinct
size jit-compiles its own per-level program — keep the number of distinct
sizes small); empty chunks are not allowed. This trainer matches the
in-memory Driver bitwise on the same data (tests/test_streaming.py),
except at exact bf16-boundary candidate ties where the chunked f32
summation order can legitimately pick the other side (~1 node per 160k,
measured — ops/split.py "Determinism boundary").

Distribution composes: each chunk is row-sharded over the TPUDevice mesh like
any other upload, so a v5e-64 pod streams 8 host-chunks in parallel while each
chunk's histogram psum rides ICI (SURVEY.md §7 M6).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable

import numpy as np

from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble, empty_ensemble
from ddt_tpu.reference.numpy_trainer import grad_hess
from ddt_tpu.telemetry import costmodel
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry.annotations import phase_ctx
from ddt_tpu.ops.grow import resolve_hist_subtraction
from ddt_tpu.telemetry.events import (
    PartitionRecorder, RoundRecorder, RunLog, comms_manifest_fields,
    derive_run_id, emit_early_stop, emit_train_heartbeat, finish_run_log)
from ddt_tpu.utils import checkpoint
from ddt_tpu.utils.profiling import PhaseTimer

log = logging.getLogger("ddt_tpu.streaming")

ChunkFn = Callable[[int], tuple[np.ndarray, np.ndarray]]


def _emit_round(run_log: "RunLog | None", rnd: int, ms: float,
                ev: "_StreamEval | None", status=None) -> None:
    """Streaming round event: ms + the round's eval score when tracked
    (train loss is deliberately absent — computing it would cost an extra
    full pass over the chunks). Also the streamed loops' round-boundary
    progress hook: bumps the train_rounds counter and, when a live
    TrainStatus is attached (cli --status-port), pushes the round into
    its rolling window/ring."""
    tele_counters.record_train_round()
    if run_log is None and status is None:
        return
    val_score = None
    if ev is not None and ev.history:
        last = ev.history[-1]
        if last.get("round") == rnd + 1:
            val_score = last.get(f"valid_{ev.metric}")
    rec = RoundRecorder.make_record(rnd, ms, None,
                                    ev.metric if ev is not None else None,
                                    val_score)
    if run_log is not None:
        run_log.emit("round", **rec)
    if status is not None:
        status.round_end(rnd, ms, rec)


def validate_mapper_config(mapper, cfg: TrainConfig) -> None:
    """The mapper↔config consistency guards api.train enforces, for the
    streaming paths (a mismatched mapper trains a silently wrong model,
    not a crashing one)."""
    if mapper.n_bins != cfg.n_bins:
        raise ValueError(
            f"mapper was fitted with n_bins={mapper.n_bins} but "
            f"cfg.n_bins={cfg.n_bins}"
        )
    if (cfg.missing_policy == "learn") != mapper.missing_bin:
        raise ValueError(
            f"mapper.missing_bin={mapper.missing_bin} but "
            f"cfg.missing_policy={cfg.missing_policy!r}; refit the mapper "
            "with the same policy"
        )
    if cfg.cat_features:
        bad = mapper.non_identity_columns(cfg.cat_features)
        if bad:
            raise ValueError(
                f"cat_features {bad} were not identity-binned by this "
                "mapper; refit it with "
                f"cat_features={tuple(sorted(cfg.cat_features))}"
            )


def binned_chunks(chunk_fn: ChunkFn, mapper, cfg: TrainConfig) -> ChunkFn:
    """Adapt a RAW-float chunk source into the binned source
    fit_streaming consumes, via a fitted BinMapper (see
    data/quantizer.fit_bin_mapper_streaming for fitting one without
    materialising the dataset). Purity is preserved: any chunk still
    regenerates anywhere, bins included — which also means every re-read
    re-bins; callers whose binned chunks fit somewhere can cache them.

    `cfg` is required so the mapper↔config consistency guards that
    api.train enforces hold on this path too."""
    validate_mapper_config(mapper, cfg)

    def f(c: int):
        X, y = chunk_fn(c)
        return mapper.transform(np.asarray(X, np.float32)), y

    # Side-channel accessors so fit_streaming's label-only pass 0 and
    # shape probe skip the (expensive) binning of chunks they would
    # otherwise transform and throw away.
    f.labels = lambda c: chunk_fn(c)[1]
    f.n_features = mapper.n_features
    return f


def _go_right(
    fv: np.ndarray,           # winning-column bin values for the live rows
    nodes: np.ndarray,        # their heap slots
    feature: np.ndarray,
    threshold_bin: np.ndarray,
    default_left: np.ndarray | None,
    missing_bin_value: int,
    cat_features: tuple,
) -> np.ndarray:
    """Routing decision with the full split semantics (ordinal,
    categorical one-vs-rest, reserved-NaN-bin default direction) — the
    single host home of the streamed routing rule."""
    thr = threshold_bin[nodes]
    go_right = fv > thr
    if cat_features:
        cat = np.isin(feature[nodes], cat_features)
        go_right = np.where(cat, fv != thr, go_right)
    if missing_bin_value >= 0:
        go_right = np.where(fv == missing_bin_value,
                            ~default_left[nodes], go_right)
    return go_right


def _traverse_partial(
    Xb: np.ndarray,
    feature: np.ndarray,
    threshold_bin: np.ndarray,
    is_leaf: np.ndarray,
    depth: int,
    default_left: np.ndarray | None = None,
    missing_bin_value: int = -1,
    cat_features: tuple = (),
) -> np.ndarray:
    """Stateless node assignment at `depth`: heap slot per row, or -1 when the
    row froze at a leaf above this level. Mirrors the in-memory grow loop's
    (node_id, frozen) evolution exactly."""
    R = Xb.shape[0]
    node = np.zeros(R, np.int64)
    frozen = np.zeros(R, bool)
    for d in range(depth):
        live = ~frozen & ~is_leaf[node]
        frozen |= is_leaf[node]
        f = feature[node[live]]
        fv = Xb[live, f].astype(np.int64)
        go_right = _go_right(fv, node[live], feature, threshold_bin,
                             default_left, missing_bin_value, cat_features)
        node[live] = 2 * node[live] + 1 + go_right
    offset = (1 << depth) - 1
    out = (node - offset).astype(np.int32)
    out[frozen] = -1
    return out


def _apply_level_splits(
    hist: np.ndarray,
    cfg: TrainConfig,
    depth: int,
    feature: np.ndarray,
    threshold_bin: np.ndarray,
    is_leaf: np.ndarray,
    leaf_value: np.ndarray,
    split_gain: np.ndarray,
    default_left: np.ndarray | None = None,
    feature_mask: np.ndarray | None = None,
) -> None:
    """Level-`depth` split decisions from the accumulated histogram,
    written into the node arrays in place. The SINGLE home of the
    streamed split rule — both the host and device loops call this, so
    host/device bit-identity cannot drift. `feature_mask` is the round's
    colsample mask (ops/sampling.colsample_mask — the identical rule the
    Driver applies inside grow: masked features never win the argmax)."""
    from ddt_tpu.reference.numpy_trainer import best_splits, node_totals

    n_level = 1 << depth
    offset = n_level - 1
    G, H = node_totals(hist)
    cat_mask = None
    if cfg.cat_features:
        cat_mask = np.zeros(hist.shape[1], bool)
        cat_mask[list(cfg.cat_features)] = True
    gains, feats, bins, dls = best_splits(
        hist, cfg.reg_lambda, cfg.min_child_weight,
        feature_mask=feature_mask,
        missing_bin=cfg.missing_policy == "learn", cat_mask=cat_mask)
    with np.errstate(divide="ignore", invalid="ignore"):   # empty nodes
        value = np.where(H > 0, -G / (H + cfg.reg_lambda), 0.0).astype(
            np.float32)
    do_split = (gains > cfg.min_split_gain) & np.isfinite(gains) & (H > 0)
    for i in range(n_level):
        slot = offset + i
        if do_split[i]:
            feature[slot] = feats[i]
            threshold_bin[slot] = bins[i]
            split_gain[slot] = gains[i]
            if default_left is not None:
                default_left[slot] = dls[i]
        else:
            is_leaf[slot] = True
            leaf_value[slot] = value[i]


def _assemble_subtracted_level(
    parent_hist: np.ndarray,     # [2^(d-1), F, B, 2]: previous level's
    #                              fully-ACCUMULATED histograms
    left: np.ndarray,            # [2^(d-1), F, B, 2]: this level's
    #                              accumulated LEFT-child histograms
    is_leaf: np.ndarray,
    depth: int,
) -> np.ndarray:
    """Sibling-subtraction assembly for the streamed host accumulator —
    the host twin of ops/grow.level_histograms' subtract branch: right
    child = parent - left, gated to exactly zero for children of parents
    that did NOT split (a frozen parent's phantom right child would
    otherwise inherit the full parent mass), interleaved back to level
    order (left = 2p, right = 2p + 1). Dtype-generic: quantized-gradient
    levels carry int32 accumulations, where the subtraction is EXACT
    (the f32-ULP right-child seam does not exist on that path)."""
    half = 1 << (depth - 1)
    offset = half - 1
    gate = ~is_leaf[offset:offset + half]
    right = np.where(gate[:, None, None, None],
                     parent_hist - left, left.dtype.type(0))
    out = np.empty((2 * half,) + left.shape[1:], left.dtype)
    out[0::2] = left
    out[1::2] = right
    return out


def _apply_final_leaves(
    Gl: np.ndarray,
    Hl: np.ndarray,
    cfg: TrainConfig,
    is_leaf: np.ndarray,
    leaf_value: np.ndarray,
) -> None:
    """Final-level leaf values from streamed (G, H) aggregates (shared by
    the host and device loops)."""
    n_last = 1 << cfg.max_depth
    offset = n_last - 1
    with np.errstate(divide="ignore", invalid="ignore"):   # empty nodes
        vals = np.where(Hl > 0, -Gl / (Hl + cfg.reg_lambda), 0.0)
    is_leaf[offset:offset + n_last] = True
    leaf_value[offset:offset + n_last] = vals.astype(np.float32)


class _StreamEval:
    """Held-out-chunk validation for the streaming trainers (round-2
    verdict item 3): per-round metric over streamed validation chunks,
    best-round tracking, early stopping. Metrics evaluate on HOST in f64
    over the concatenated per-chunk raw scores — the Driver's host eval
    path, so auc works and stopping decisions are backend-invariant (the
    f32 device-metric boundary documented in driver.py does not apply
    here). Validation labels are O(val rows) host state — the val set is
    the small fraction; the 10B-row axis being streamed is the train set.
    """

    def __init__(self, valid_chunk_fn: ChunkFn, n_valid_chunks: int,
                 metric_name: str | None, loss: str,
                 early_stopping_rounds: int | None,
                 history: list | None):
        from ddt_tpu.utils.metrics import GREATER_IS_BETTER, default_metric

        if n_valid_chunks < 1:
            raise ValueError("validation needs n_valid_chunks >= 1")
        self.fn = valid_chunk_fn
        self.n = n_valid_chunks
        self.metric = metric_name or default_metric(loss)
        if self.metric not in GREATER_IS_BETTER:
            raise ValueError(
                f"unknown metric {self.metric!r}; "
                f"have {sorted(GREATER_IS_BETTER)}"
            )
        if self.metric == "auc" and loss == "softmax":
            # Same guard as Driver.fit: the rank formulation is binary,
            # and multiclass raw scores crash deep inside the host auc.
            raise ValueError(
                "auc is a binary metric; softmax validation supports "
                "logloss or accuracy"
            )
        self.sign = 1.0 if GREATER_IS_BETTER[self.metric] else -1.0
        self.patience = early_stopping_rounds
        self.history = history if history is not None else []
        labels_of = getattr(valid_chunk_fn, "labels", None) or (
            lambda c: valid_chunk_fn(c)[1])
        ys = [np.asarray(labels_of(c)) for c in range(self.n)]
        if any(len(y) == 0 for y in ys):
            raise ValueError("empty validation chunks are not allowed")
        self._ys = ys
        self.y = np.concatenate(ys)
        self.lens = [len(y) for y in ys]
        self.best = -np.inf
        self.best_round: int | None = None
        self.best_score: float | None = None

    def labels(self, c: int) -> np.ndarray:
        """Chunk c's labels WITHOUT re-reading (or re-binning) the chunk."""
        return self._ys[c]

    def record(self, rnd: int, raw_scores: np.ndarray) -> bool:
        """Score round `rnd` from the concatenated raw validation scores;
        returns True when early stopping says stop AFTER this round."""
        from ddt_tpu.utils.metrics import evaluate

        s = evaluate(self.metric, self.y, raw_scores)
        self.history.append({"round": rnd + 1, f"valid_{self.metric}": s})
        log.info("streaming: round %d valid_%s=%.6f", rnd + 1, self.metric,
                 s)
        if self.sign * s > self.best:
            self.best = self.sign * s
            self.best_round = rnd
            self.best_score = s
        if self.patience is None:
            return False
        if self.best_round is None:
            # Same guard as Driver.fit: NaN never improves on -inf.
            raise ValueError(
                f"validation {self.metric} has been NaN since round 1 "
                "(degenerate validation chunks); cannot early-stop on it"
            )
        return rnd - self.best_round >= self.patience


# Default HBM budget for the device-resident chunk cache: big enough to
# hold mid-size out-of-core datasets entirely (a v5e core has 16 GB),
# small enough to leave the working set (histograms, preds, pipeline
# buffers) ample headroom.
DEVICE_CHUNK_CACHE_BYTES = 6 << 30


class _DeviceChunkCache:
    """Memoises `backend.upload(chunk)` per chunk index up to a shared
    byte budget. Streamed training re-reads every chunk (max_depth + 1)
    times per tree; when the binned chunks fit in device memory, paying
    the host→device transfer once and serving every later pass from HBM
    removes the pipeline's transfer bound entirely (measured: the
    remote-tunnel 20M x 64 run drops from transfer-bound to compute-
    bound — docs/PERF.md round-4). Chunks past the budget simply upload
    per use, preserving O(working-set) device memory for datasets that
    do not fit. Safe because no stream op donates its data operand
    (backends/tpu.py _stream_fn: only pred is donated)."""

    def __init__(self, backend, chunk_fn, budget: list):
        self._backend = backend
        self._chunk_fn = chunk_fn
        self._budget = budget          # [remaining_bytes], shared train/val
        self._cached: dict = {}        # c -> (handle, nbytes)

    def _upload(self, c: int):
        """One chunk's device handle — via the host-sharded per-process
        assembly when the source is per-host-addressable
        (data.chunks.HostShardedChunks + TPUDevice.upload_row_shards:
        this process reads ONLY its own sub-shards), else the classic
        full-chunk read + row-sharded upload."""
        src = self._chunk_fn
        if getattr(src, "host_sharded", False) and \
                getattr(self._backend, "upload_row_shards", None) \
                is not None:
            parts = [src.read_part(c, s) for s in src.owned_slots(c)]
            return self._backend.upload_row_shards(parts,
                                                   src.chunk_rows(c))
        Xc = np.asarray(src(c)[0])
        return self._backend.upload(Xc)

    def get(self, c: int):
        hit = self._cached.get(c)
        if hit is not None:
            return hit[0]
        h = self._upload(c)
        # Budget accounting uses the handle's ACTUAL per-process device
        # footprint (upload pads rows to the shard count and uneven chunk
        # sizes pad differently, so host-side Xc.nbytes undercounts).
        # Summing addressable shards is per-process by construction —
        # exactly what a per-process HBM budget should track.
        try:
            nbytes = sum(s.data.nbytes for s in h.addressable_shards)
        except (AttributeError, TypeError):
            nbytes = int(np.asarray(h).nbytes)   # host arrays: no shards
        if nbytes <= self._budget[0]:
            self._budget[0] -= nbytes
            self._cached[c] = (h, nbytes)
        return h

    def clear(self) -> None:
        """Drop every cached handle and refund the budget — the streamed
        re-partition rebuilt the mesh, so cached placements are stale
        (the next get() re-uploads onto the rotated device order)."""
        for _, nbytes in self._cached.values():
            self._budget[0] += nbytes
        self._cached.clear()


def fit_streaming(
    chunk_fn: ChunkFn,
    n_chunks: int,
    cfg: TrainConfig,
    backend=None,
    cache_preds: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 25,
    valid_chunk_fn: ChunkFn | None = None,
    n_valid_chunks: int = 0,
    eval_metric: str | None = None,
    early_stopping_rounds: int | None = None,
    history: list | None = None,
    device_chunk_cache: "bool | int" = True,
    run_log: "RunLog | str | None" = None,
    profile: bool = False,
    profiler_window=None,
    status=None,
) -> TreeEnsemble:
    """Train a GBDT over streamed chunks — see _fit_streaming_impl
    directly below for the full contract (validation, checkpointing,
    device streaming, sampling, telemetry). This wrapper owns exactly
    one concern: run-scoped state built HERE — a run log coerced from a
    path string, the cost-capture collector, a still-open xprof window,
    the robustness fault sink, a cfg.fault_plan chaos plan — is torn
    down on every exit, success or mid-run exception (the Driver has
    the same shim on fit), so repeated failing fits cannot leak file
    handles or bill capture work to later runs.

    The chunk sources are additionally wrapped in the stream-read retry
    seam (utils/retry.retrying_chunk_fn): every read — training, value
    and label-only alike, on both the host and device loops — retries
    transient I/O faults with jittered backoff, each failed attempt
    emitting a schema'd `fault` event. Chunk sources are pure by
    contract, so a retried re-read changes nothing."""
    from ddt_tpu.robustness import faultplan, set_fault_sink
    from ddt_tpu.utils import retry as retry_lib

    # Load the plan BEFORE touching any process-global state: a bad plan
    # file must fail clean, not leak the sink or the cost collector.
    plan = None
    if cfg.fault_plan and faultplan.active_plan() is None:
        plan = faultplan.load_plan(cfg.fault_plan)
    own_run_log = isinstance(run_log, str)
    run_log = RunLog.coerce(run_log)
    # Device-truth cost capture (telemetry/costmodel.py): telemetry runs
    # only; torn down below even when the fit dies mid-round.
    cost = costmodel.activate() if run_log is not None else None
    prev_sink = set_fault_sink(run_log)
    plan_prev = None
    plan_armed = False
    if plan is not None:
        plan_prev = faultplan.activate(plan)
        plan_armed = True
    chunk_fn = retry_lib.retrying_chunk_fn(chunk_fn)
    if valid_chunk_fn is not None:
        valid_chunk_fn = retry_lib.retrying_chunk_fn(valid_chunk_fn)
    try:
        return _fit_streaming_impl(
            chunk_fn, n_chunks, cfg, backend=backend,
            cache_preds=cache_preds, checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            valid_chunk_fn=valid_chunk_fn, n_valid_chunks=n_valid_chunks,
            eval_metric=eval_metric,
            early_stopping_rounds=early_stopping_rounds, history=history,
            device_chunk_cache=device_chunk_cache, run_log=run_log,
            profile=profile, cost_collector=cost,
            profiler_window=profiler_window, status=status)
    finally:
        costmodel.deactivate(cost)
        if profiler_window is not None:
            profiler_window.close()
        if plan_armed:
            faultplan.deactivate(plan_prev)
        set_fault_sink(prev_sink)
        if own_run_log and run_log is not None:
            run_log.close()


def _fit_streaming_impl(
    chunk_fn: ChunkFn,
    n_chunks: int,
    cfg: TrainConfig,
    backend=None,
    cache_preds: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 25,
    valid_chunk_fn: ChunkFn | None = None,
    n_valid_chunks: int = 0,
    eval_metric: str | None = None,
    early_stopping_rounds: int | None = None,
    history: list | None = None,
    device_chunk_cache: "bool | int" = True,
    run_log: "RunLog | None" = None,
    profile: bool = False,
    cost_collector=None,
    profiler_window=None,
    status=None,
) -> TreeEnsemble:
    """Train a GBDT over `n_chunks` streamed chunks.

    Observability: `run_log` (a JSONL path or telemetry.RunLog) emits the
    same schema-versioned event stream as Driver.fit — run manifest,
    per-round records (with the round's eval metric when validation is
    on), per-phase timings, resume events, device counters — rendered by
    `python -m ddt_tpu.cli report`. `profile=True` additionally logs the
    PhaseTimer breakdown at INFO; either flag turns phase timing on
    (host wallclock per hist/gain/leaf/predict/eval phase; the streamed
    loops' natural pass boundaries already sync, so no extra barriers
    are added).

    Validation/early stopping (round-2 verdict item 3): pass held-out
    chunks via `valid_chunk_fn`/`n_valid_chunks` — each round's freshly
    grown trees are applied to per-chunk validation predictions (device-
    resident on device backends, exactly like the training state) and the
    metric is recorded in `history` ({"round", "valid_<metric>"}, the
    Driver's record shape). With `early_stopping_rounds=k`, training
    stops after k rounds without improvement and the returned ensemble is
    truncated to the best round — identical truncation semantics to
    Driver.fit. On checkpoint resume, best-round tracking restarts at the
    resume round (earlier rounds' scores are not re-evaluated).

    Device backends exposing the stream_* surface (TPUDevice) run the
    whole per-(chunk, level) step on device — traversal, grads, histogram,
    psum — with the NEXT chunk's upload overlapping the current chunk's
    compute, and per-chunk boosting state (pred, labels) resident on
    device for the whole run (ops/stream.py; supports softmax and
    n_partitions/host_partitions > 1). Host backends stream the host
    formulation (binary/mse/softmax — one tree per class per round from
    round-start preds, like the Driver). Both match the in-memory Driver
    on the same data bitwise — including missing_policy='learn'
    (reserved NaN bin + learned default directions) and categorical
    one-vs-rest splits (tests/test_streaming.py) — except when a node's
    two best candidate gains are exact bf16-boundary ties, where the
    chunked host accumulation's f32 summation order can legitimately
    pick the other candidate (~1 node per 160k, measured; ops/split.py
    "Determinism boundary", chunked-accumulation paragraph).

    Sampling configs stream too (round-4 verdict item 2): bagging keeps
    a row by the stateless counter-based hash of (seed, round, GLOBAL
    row id) — ops/sampling — computed per chunk from the chunk's row
    offset (O(chunk), on device on the device path), and colsample draws
    the same per-(round, class) host masks as the Driver, applied at the
    shared split-selection home (_apply_level_splits). Both therefore
    grow the in-memory Driver's exact trees, same contract (and same
    bf16-boundary-tie seam) as deterministic streaming.

    `device_chunk_cache` (device backends only): True caches uploaded
    binned chunks in device memory up to DEVICE_CHUNK_CACHE_BYTES —
    but only when the device has memory of its own (on a CPU-platform
    run the "device" IS host RAM, so True degrades to no caching there:
    pinning min(dataset, 6 GiB) of host memory would break the O(chunk)
    host contract this trainer exists for). An int budget is always
    honored verbatim (that is how the CPU-platform tests force the
    cache on); False re-uploads every pass (the pre-round-4 behavior).
    Caching changes no results — the same buffers feed the same ops —
    only how often the H2D link is paid: once per chunk instead of
    (max_depth + 1) times per tree. Host memory stays O(chunk); device
    memory grows to min(dataset, budget).
    """
    if backend is None:
        from ddt_tpu.backends import get_backend

        backend = get_backend(cfg)

    device = hasattr(backend, "stream_level_hist")
    if cfg.grad_dtype != "f32" and not device:
        # The quantized path's per-round scale pass and integer builds
        # are device ops (backends/tpu.py stream_grad_stats /
        # stream_level_hist); the host loop's numpy builders have no
        # integer twin. Refuse loudly — a silently-f32 "quantized" run
        # is worse than an error (backend='tpu' runs on CPU XLA too).
        raise NotImplementedError(
            f"grad_dtype={cfg.grad_dtype!r} streaming requires a device "
            "backend exposing the stream_* surface (backend='tpu'); the "
            "host streaming loop has no integer histogram path")

    # Telemetry prologue — BEFORE pass 0 so the transfer counters see the
    # label uploads; host-side bookkeeping only (no device syncs), and
    # everything below is skipped when run_log is None and profile False.
    t_fit0 = time.perf_counter()
    counters_start = None
    timer = PhaseTimer() if (profile or run_log is not None) else None
    ph = phase_ctx(timer)
    if run_log is not None:
        tele_counters.install_jax_listener()
        counters_start = tele_counters.snapshot()

    # Pass 0: base score from running label sums + shape discovery — no
    # O(R) host state anywhere in this trainer except the optional preds
    # cache (see below); at the 10B-row target everything else is O(chunk).
    # Device backends also ship labels NOW (one read of each chunk, not a
    # second pass): labels stay device-resident for the whole run.
    y_sum, y_cnt = 0.0, 0
    chunk_lens = []
    y_dev = []
    # binned_chunks-style adapters expose a label-only accessor so this
    # pass doesn't pay for binning feature matrices it never reads.
    labels_of = getattr(chunk_fn, "labels", None) or (
        lambda c: chunk_fn(c)[1])
    for c in range(n_chunks):
        yc = labels_of(c)
        if len(yc) == 0:
            # Fail HERE, at the cause — a zero-row chunk otherwise dies
            # far away (device shard padding / NaN base score).
            raise ValueError(
                f"chunk {c} is empty; empty chunks are not allowed "
                "(re-cut the chunk boundaries)"
            )
        y_sum += float(np.sum(yc))
        y_cnt += len(yc)
        chunk_lens.append(len(yc))
        if device:
            y_dev.append(backend.upload_labels(np.asarray(yc)))
    # Global row offset per chunk — the bagging hash is a function of a
    # row's GLOBAL id, so chunk boundaries cannot change the masks.
    chunk_starts = np.concatenate(
        [[0], np.cumsum(chunk_lens)]).astype(np.int64)
    mean = y_sum / max(1, y_cnt)
    if cfg.loss == "logloss":
        p_ = float(np.clip(mean, 1e-6, 1 - 1e-6))
        bs = float(np.log(p_ / (1 - p_)))
    elif cfg.loss == "softmax":
        bs = 0.0
    else:
        bs = float(mean)
    F = getattr(chunk_fn, "n_features", None)
    if F is None:
        F = chunk_fn(0)[0].shape[1]

    C = cfg.n_classes if cfg.loss == "softmax" else 1
    ens = empty_ensemble(
        cfg.n_trees * C, cfg.max_depth, F, cfg.learning_rate, bs,
        cfg.loss, cfg.n_classes,
        missing_bin=cfg.missing_policy == "learn", n_bins=cfg.n_bins,
        cat_features=cfg.cat_features,
    )

    trainer_name = "streaming_device" if device else "streaming_host"
    # Deterministic config digest: the v2 merge key AND the xprof
    # window's trace-dir name — computed whenever either consumer wants
    # it (the FULL config feeds it so sweep points differing in any
    # field refuse to merge).
    run_id = None
    if (run_log is not None or profiler_window is not None
            or status is not None):
        run_id = derive_run_id(
            trainer=trainer_name, rows=int(y_cnt), features=int(F),
            n_chunks=n_chunks, **dataclasses.asdict(cfg))
    if profiler_window is not None:
        profiler_window.bind(run_id)
    if status is not None:
        # Live status daemon (telemetry/statusd.py) — seed the run
        # identity/denominators before round 0 so the first scrape
        # already answers "which run, how far along".
        status.begin_run(run_id=run_id, total_rounds=cfg.n_trees,
                         rows=int(y_cnt))
    if run_log is not None:
        run_log.run_id = run_id
        run_log.emit(
            "run_manifest",
            trainer=trainer_name,
            backend=getattr(backend, "name", "unknown"), loss=cfg.loss,
            n_trees=cfg.n_trees, max_depth=cfg.max_depth,
            n_bins=cfg.n_bins, rows=int(y_cnt), features=int(F),
            n_classes=C, seed=cfg.seed, n_chunks=n_chunks,
            distributed=bool(getattr(backend, "distributed", False)),
            run_id=run_id,
            host=int(getattr(backend, "host_index", 0)),
            **comms_manifest_fields(backend),
            # v3 extras: the xprof cross-reference (telemetry/profiler).
            **(profiler_window.manifest_fields()
               if profiler_window is not None else {}))

    # Per-partition attribution for mesh runs (inert otherwise — the
    # recorder only probes when distributed AND a run log is attached;
    # the streamed estimate is per chunk-pass, n_chunks allreduces/round).
    part_rec = PartitionRecorder(
        run_log, backend,
        bytes_per_round=(
            C * n_chunks * backend.collective_bytes_per_tree(
                int(F), streamed=True)
            if getattr(backend, "distributed", False) else 0))
    # Straggler watchdog (robustness/watchdog.py) — detection always
    # (fault events per trip); behind cfg.straggler_repartition the
    # DEVICE streaming loop also ACTS at checkpoint-cadence boundaries:
    # mesh rotation + resident-state reshard + chunk-cache drop + a
    # host-sharded source's chunk-shard->host assignment rotation
    # (bit-identical by construction — the rotate_row_partitions
    # contract extended to the streamed path, ROADMAP item 2). Exists
    # exactly when the recorder is active.
    watchdog = None
    if part_rec.active:
        from ddt_tpu.robustness.watchdog import StragglerWatchdog

        watchdog = StragglerWatchdog(
            threshold=cfg.straggler_skew_threshold)

    def _finish(e: TreeEnsemble) -> TreeEnsemble:
        """Telemetry epilogue — every fit_streaming return funnels
        through here (the early-stop returns included) so a run log is
        always terminated by the shared phase_timings/counters/run_end
        sequence (telemetry.events.finish_run_log; the owning wrapper
        closes path-built logs)."""
        if profile and timer is not None:
            timer.log_report(log)
        if status is not None:
            status.set_phase("done")
        finish_run_log(run_log, timer, counters_start, e.n_trees // C,
                       round(time.perf_counter() - t_fit0, 4),
                       partitions=part_rec, costs=cost_collector)
        return e

    # Checkpoint/resume (SURVEY.md §5) — the streamed runs are the LONGEST
    # ones, so restartability matters most here. Boosting state is
    # reconstituted by rescoring the restored partial ensemble per chunk
    # with the Driver's per-round accumulation order (bit-exact resume).
    start_round = 0
    if checkpoint_dir is not None:
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        from ddt_tpu.utils.checkpoint import try_resume

        start_round = try_resume(checkpoint_dir, ens, cfg,
                                 run_log=run_log)
        if start_round > 0:
            log.info("streaming: resumed from checkpoint at round %d",
                     start_round)
            if run_log is not None:
                run_log.emit("fault", kind="checkpoint_resume",
                             round=start_round)
        if start_round >= cfg.n_trees:
            # Already finished (e.g. a preemptible-restart loop re-runs
            # the command): return the restored ensemble without the full
            # boosting-state reconstitution pass over the dataset.
            return _finish(ens)

    if early_stopping_rounds is not None and valid_chunk_fn is None:
        raise ValueError("early_stopping_rounds requires valid_chunk_fn")
    ev = None
    if valid_chunk_fn is not None:
        ev = _StreamEval(valid_chunk_fn, n_valid_chunks, eval_metric,
                         cfg.loss, early_stopping_rounds, history)

    if device:
        return _finish(_fit_streaming_device(
            chunk_fn, n_chunks, cfg, backend, ens, bs, C, y_dev,
            chunk_starts,
            start_round=start_round, checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every, ev=ev,
            device_chunk_cache=device_chunk_cache,
            ph=ph, run_log=run_log, part_rec=part_rec,
            window=profiler_window, watchdog=watchdog, status=status))

    # The ONE optional O(R·C) structure: per-chunk cached raw scores (4C
    # bytes/row). cache_preds=False recomputes scores from the partial
    # ensemble instead (O(T) traversals per row per round) — choose by host
    # RAM.
    def _fresh_pred(c):
        if C > 1:
            return np.zeros((chunk_lens[c], C), np.float32)   # softmax bs=0
        return np.full(chunk_lens[c], bs, np.float32)

    preds = (
        [_fresh_pred(c) for c in range(n_chunks)] if cache_preds else None
    )
    if preds is not None and start_round > 0:
        part = ens.truncate(start_round * C)
        for c in range(n_chunks):
            preds[c] = part.predict_raw_roundwise(
                chunk_fn(c)[0], binned=True).astype(np.float32)

    # Validation predictions: host-resident per val chunk (always cached —
    # the val set is the small fraction), updated per round like the
    # Driver's incremental val_raw.
    val_preds = None
    if ev is not None:
        def _fresh_val(c):
            if C > 1:
                return np.zeros((ev.lens[c], C), np.float32)
            return np.full(ev.lens[c], bs, np.float32)

        val_preds = [_fresh_val(c) for c in range(ev.n)]
        if start_round > 0:
            part = ens.truncate(start_round * C)
            for c in range(ev.n):
                val_preds[c] = part.predict_raw_roundwise(
                    ev.fn(c)[0], binned=True).astype(np.float32)

    missing_val = cfg.missing_bin_value
    # Streamed sibling subtraction (the fused rounds' halving, extended
    # to the host accumulation loop): levels >= 1 build only LEFT-child
    # chunk histograms — half the device work AND half the streamed
    # collective payload per pass — and the right children are assembled
    # by subtraction from the previous level's ACCUMULATED histogram
    # (_assemble_subtracted_level). Platform-gated exactly like the
    # fused path (resolve_hist_subtraction): right children differ from
    # direct builds by f32 chunk-summation ULPs.
    subtract = resolve_hist_subtraction(cfg.hist_subtraction)
    coll_bytes_round = 0
    if getattr(backend, "distributed", False):
        coll_bytes_round = C * n_chunks * backend.collective_bytes_per_tree(
            F, streamed=True)
    t_out = start_round * C
    for rnd in range(start_round, cfg.n_trees):
        if profiler_window is not None:       # xprof window: start edge
            profiler_window.round_start(rnd)
        t_round = time.perf_counter()
        # Gradients for every class tree of a round come from the
        # ROUND-START preds (the Driver computes grad_hess once per round,
        # then grows C trees from its columns), so pred updates are
        # deferred until after all classes — mirroring the device loop.
        def chunk_grads(c: int, Xc, yc, cls: int):
            pred_c = preds[c] if preds is not None else _rescore(
                ens, rnd * C, Xc, bs
            )
            g, h = grad_hess(pred_c, np.asarray(yc), cfg.loss)
            if g.ndim == 2:
                g, h = g[:, cls], h[:, cls]
            if cfg.subsample < 1.0:
                from ddt_tpu.ops.sampling import row_keep_np

                keep = row_keep_np(cfg.seed, rnd, int(chunk_starts[c]),
                                   len(yc), cfg.subsample)
                g, h = g * keep, h * keep
            return g, h

        def colsample_mask_for(cls: int):
            if cfg.colsample_bytree >= 1.0:
                return None
            from ddt_tpu.ops.sampling import colsample_mask

            return colsample_mask(cfg.seed, rnd, cls, F,
                                  cfg.colsample_bytree)

        round_trees = []
        for cls in range(C):
            fmask = colsample_mask_for(cls)
            # Grow one tree level-by-level; histograms accumulate across
            # chunks.
            feature = np.full(cfg.n_nodes_total, -1, np.int32)
            threshold_bin = np.zeros(cfg.n_nodes_total, np.int32)
            is_leaf = np.zeros(cfg.n_nodes_total, bool)
            leaf_value = np.zeros(cfg.n_nodes_total, np.float32)
            split_gain = np.zeros(cfg.n_nodes_total, np.float32)
            default_left = np.zeros(cfg.n_nodes_total, bool)

            route_kw = dict(default_left=default_left,
                            missing_bin_value=missing_val,
                            cat_features=cfg.cat_features)
            prev_hist = None
            for depth in range(cfg.max_depth):
                n_level = 1 << depth
                sub = subtract and depth >= 1 and prev_hist is not None
                hist = None
                with ph("hist"):
                    for c in range(n_chunks):
                        Xc, yc = chunk_fn(c)
                        ni = _traverse_partial(
                            Xc, feature, threshold_bin, is_leaf, depth,
                            **route_kw
                        )
                        if sub:
                            # LEFT children keyed by parent slot: half
                            # the per-chunk build and half the streamed
                            # collective payload (right children come
                            # from subtraction below).
                            is_l = (ni >= 0) & (ni % 2 == 0)
                            ni = np.where(is_l, ni // 2, -1).astype(
                                np.int32)
                        g, h = chunk_grads(c, Xc, yc, cls)
                        data = backend.upload(Xc)
                        part = np.asarray(
                            backend.build_histograms(
                                data, g, h, ni,
                                n_level // 2 if sub else n_level)
                        )
                        hist = part if hist is None else hist + part
                if sub:
                    hist = _assemble_subtracted_level(prev_hist, hist,
                                                      is_leaf, depth)
                with ph("gain"):
                    _apply_level_splits(hist, cfg, depth, feature,
                                        threshold_bin, is_leaf, leaf_value,
                                        split_gain, default_left,
                                        feature_mask=fmask)
                prev_hist = hist if subtract else None

            # Final level: per-terminal (G, H) aggregates streamed the
            # same way.
            n_last = 1 << cfg.max_depth
            Gl = np.zeros(n_last, np.float32)
            Hl = np.zeros(n_last, np.float32)
            with ph("leaf"):
                for c in range(n_chunks):
                    Xc, yc = chunk_fn(c)
                    ni = _traverse_partial(
                        Xc, feature, threshold_bin, is_leaf, cfg.max_depth,
                        **route_kw
                    )
                    g, h = chunk_grads(c, Xc, yc, cls)
                    act = ni >= 0
                    np.add.at(Gl, ni[act], g[act])
                    np.add.at(Hl, ni[act], h[act])
                _apply_final_leaves(Gl, Hl, cfg, is_leaf, leaf_value)

            ens.feature[t_out] = feature
            ens.threshold_bin[t_out] = threshold_bin
            ens.is_leaf[t_out] = is_leaf
            ens.leaf_value[t_out] = leaf_value
            ens.split_gain[t_out] = split_gain
            if ens.default_left is not None:
                ens.default_left[t_out] = default_left
            t_out += 1
            round_trees.append((feature, threshold_bin, is_leaf,
                                leaf_value, default_left))

        if preds is not None:
            # leaf slot per row = heap slot where traversal stopped: either
            # offset+ni (made it to the last level) or the frozen leaf —
            # rescore via the tree to keep it simple and exact.
            with ph("predict"):
                for c in range(n_chunks):
                    Xc, _ = chunk_fn(c)
                    for cls, (feature, threshold_bin, is_leaf, leaf_value,
                              default_left) in enumerate(round_trees):
                        slot = _leaf_slot(
                            Xc, feature, threshold_bin, is_leaf,
                            cfg.max_depth,
                            default_left=default_left,
                            missing_bin_value=missing_val,
                            cat_features=cfg.cat_features,
                        )
                        dv = cfg.learning_rate * leaf_value[slot]
                        if C > 1:
                            preds[c][:, cls] += dv
                        else:
                            preds[c] += dv

        if coll_bytes_round:
            tele_counters.record_collective(coll_bytes_round)
        tele_counters.record_grad_stream(
            C * tele_counters.grad_stream_bytes(
                int(y_cnt), cfg.max_depth, cfg.grad_dtype))
        stop = False
        if ev is not None:
            with ph("eval"):
                for c in range(ev.n):
                    Xv, _ = ev.fn(c)
                    for cls, (feature, threshold_bin, is_leaf, leaf_value,
                              default_left) in enumerate(round_trees):
                        slot = _leaf_slot(
                            Xv, feature, threshold_bin, is_leaf,
                            cfg.max_depth,
                            default_left=default_left,
                            missing_bin_value=missing_val,
                            cat_features=cfg.cat_features,
                        )
                        dv = cfg.learning_rate * leaf_value[slot]
                        if C > 1:
                            val_preds[c][:, cls] += dv
                        else:
                            val_preds[c] += dv
                stop = ev.record(rnd, np.concatenate(val_preds))
        dt_ms = (time.perf_counter() - t_round) * 1e3
        _emit_round(run_log, rnd, dt_ms, ev, status=status)
        if profiler_window is not None:       # xprof window: stop edge
            profiler_window.round_end(rnd)
        if stop:
            log.info(
                "streaming: early stop at round %d (best %s=%.6f at "
                "round %d)", rnd + 1, ev.metric, ev.best_score,
                ev.best_round + 1)
            emit_early_stop(run_log, rnd + 1, ev.metric,
                            ev.best_round + 1, ev.best_score)
            ens = ens.truncate((ev.best_round + 1) * C)
            checkpoint.maybe_save(checkpoint_dir, ens, cfg,
                                  ev.best_round + 1)
            return _finish(ens)

        log.info("streaming: round %d/%d done", rnd + 1, cfg.n_trees)
        checkpoint.maybe_save(checkpoint_dir, ens, cfg, rnd + 1,
                              checkpoint_every)
        if checkpoint_every >= 1 and (rnd + 1) % checkpoint_every == 0:
            if status is not None and checkpoint_dir is not None:
                status.checkpoint_saved(rnd + 1)
            emit_train_heartbeat(
                run_log, rnd=rnd, total_rounds=cfg.n_trees,
                checkpoint_round=(rnd + 1 if checkpoint_dir is not None
                                  else None),
                ms_per_round=dt_ms)

    checkpoint.maybe_save(checkpoint_dir, ens, cfg, cfg.n_trees)
    return _finish(ens)


def _merge_quant_stats(acc, st):
    """Host reduction of per-chunk quantization stats [C, 4] (max|g|,
    sum|g|, max|h|, sum|h|): maxes max exactly, sums accumulate in f64
    (the f32 cast happens once inside quant_scale_np; chunk-order ULPs
    are absorbed by the power-of-two scale snap — ops/grad)."""
    st = np.asarray(st, np.float64)
    if acc is None:
        return st
    out = acc.copy()
    out[:, 0] = np.maximum(acc[:, 0], st[:, 0])
    out[:, 2] = np.maximum(acc[:, 2], st[:, 2])
    out[:, 1] = acc[:, 1] + st[:, 1]
    out[:, 3] = acc[:, 3] + st[:, 3]
    return out


def _fit_streaming_device(
    chunk_fn: ChunkFn,
    n_chunks: int,
    cfg: TrainConfig,
    backend,
    ens: TreeEnsemble,
    bs: float,
    C: int,
    y_dev: list,
    chunk_starts: np.ndarray,
    start_round: int = 0,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 25,
    ev: "_StreamEval | None" = None,
    device_chunk_cache: "bool | int" = True,
    ph=None,
    run_log: "RunLog | None" = None,
    part_rec: "PartitionRecorder | None" = None,
    window=None,
    watchdog=None,
    status=None,
) -> TreeEnsemble:
    """Device streaming loop: see fit_streaming. Per tree it makes
    max_depth histogram passes + 1 leaf pass (+ 1 pred-update pass between
    rounds) over the chunks; each pass re-reads only Xb (uint8 —
    pred/labels stay device-resident) — from the device chunk cache when
    it fits the budget, else re-uploaded with the next chunk's host read
    + H2D upload enqueued BEFORE the current chunk's small output is
    fetched, so the transfer rides under the device compute (double
    buffering via JAX's async dispatch)."""
    if ph is None:
        ph = phase_ctx(None)
    if part_rec is None:
        part_rec = PartitionRecorder(None, backend)      # inert
    if device_chunk_cache is True:
        # Platform guard (see fit_streaming's docstring): on the CPU
        # platform the device buffers ARE host RAM — a default-on cache
        # would pin the dataset in host memory. Real accelerators cache.
        import jax

        on_host = jax.default_backend() == "cpu"
        cache_budget = [0 if on_host else DEVICE_CHUNK_CACHE_BYTES]
    elif device_chunk_cache is False:
        cache_budget = [0]
    else:
        cache_budget = [int(device_chunk_cache)]
    chunks = _DeviceChunkCache(backend, chunk_fn, cache_budget)
    val_chunks = (_DeviceChunkCache(backend, ev.fn, cache_budget)
                  if ev is not None else None)
    # Device-resident per-chunk boosting state (labels were shipped during
    # pass 0): pred for the whole run — 4C bytes/row, row-sharded over the
    # mesh like the data, per-chip tiny next to the streamed Xb.
    pred_dev = [backend.init_pred(h, bs) for h in y_dev]
    # Validation predictions: device-resident per val chunk, updated per
    # round by the same stream_update_pred op as the training state; the
    # raw scores are fetched each round for host-side (f64) metric
    # evaluation.
    val_pred = None
    if ev is not None:
        # ev.labels avoids re-reading (and, through a binned_chunks
        # adapter, re-binning) each val chunk just for its labels; the
        # handles exist for init_pred's padded row shape + validity mask.
        val_y_dev = [backend.upload_labels(ev.labels(c))
                     for c in range(ev.n)]
        val_pred = [backend.init_pred(h, bs) for h in val_y_dev]
    if start_round > 0:
        # Resume: REPLAY the identical device update ops over the restored
        # trees (rounds ascending, classes ascending — the training
        # order). Host rescoring would differ by FMA-contraction ULPs
        # (XLA fuses pred + lr*dv into one rounding); replaying the same
        # compiled op is bit-exact vs an uninterrupted run by
        # construction. One upload pass over the chunks, start_round*C
        # cheap update dispatches each.
        def _replay(preds_list, src_of, n_of):
            for c in range(n_of):
                data = src_of.get(c)
                for r in range(start_round):
                    for cls in range(C):
                        slot = r * C + cls
                        tree_full = (
                            ens.feature[slot], ens.threshold_bin[slot],
                            ens.is_leaf[slot], ens.leaf_value[slot],
                            ens.default_left[slot],
                        )
                        preds_list[c] = backend.stream_update_pred(
                            data, preds_list[c], tree_full, cfg.max_depth,
                            cls)

        _replay(pred_dev, chunks, n_chunks)
        if ev is not None:
            _replay(val_pred, val_chunks, ev.n)

    n_feat = ens.n_features

    def passes(tree, depth, kind, class_idx, rnd, build_left=False,
               scales=None):
        """One full pass over the chunks; yields per-chunk device outputs
        with the next read/upload already in flight. Histogram outputs
        are sliced back to the real feature count (reduce-scatter mode
        pads F to the shard count with zero columns). `scales` is the
        round's (gscale, hscale) under quantized gradients — outputs
        are then RAW int32 partials the caller accumulates exactly and
        dequantizes once per level."""
        data = chunks.get(0)
        for c in range(n_chunks):
            tc0 = time.perf_counter()
            if kind == "hist":
                out = backend.stream_level_hist(
                    data, pred_dev[c], y_dev[c], tree, depth, class_idx,
                    rnd=rnd, row_start=int(chunk_starts[c]),
                    build_left=build_left, quant_scales=scales)
            else:
                out = backend.stream_leaf_gh(
                    data, pred_dev[c], y_dev[c], tree, depth, class_idx,
                    rnd=rnd, row_start=int(chunk_starts[c]),
                    quant_scales=scales)
            if c + 1 < n_chunks:        # prefetch: overlap H2D with compute
                data = chunks.get(c + 1)
            # Flight recorder: per-device completion of this chunk's pass
            # — AFTER the prefetch enqueue so the probe barrier rides
            # under the next chunk's H2D; the asarray below was already
            # a sync, so active-recorder cost is the probe bookkeeping.
            part_rec.observe(kind, out, tc0)
            part = np.asarray(out)      # fetch (device likely done by now)
            if kind == "hist" and part.shape[1] != n_feat:
                part = part[:, :n_feat]     # drop scatter pad columns
            yield part

    t_out = start_round * C
    # The previous round's finished trees, NOT yet applied to the resident
    # preds: the application is folded into the NEXT round's first data
    # pass (stream_round_start) — one pass where round 2 used to spend two
    # (round-2 verdict item 6). The final round's trees are never applied
    # (pred is dead after the last gradients — same as the old loop, which
    # skipped its trailing update pass).
    prev_trees = None
    quant = cfg.grad_dtype != "f32"
    subtract = resolve_hist_subtraction(cfg.hist_subtraction,
                                        integer_hists=quant)
    coll_bytes_round = 0
    if getattr(backend, "distributed", False):
        coll_bytes_round = C * n_chunks * backend.collective_bytes_per_tree(
            ens.n_features, streamed=True)
    for rnd in range(start_round, cfg.n_trees):
        if window is not None:                # xprof window: start edge
            window.round_start(rnd)
        t_round = time.perf_counter()
        # Quantized gradients (cfg.grad_dtype): the round's per-class
        # scales must exist BEFORE any histogram build, so the round
        # opens with a stats pass — FUSED into the previous round's
        # tree application (stream_round_start returns [C, 4] stats
        # instead of a depth-0 histogram; the depth-0 build then runs
        # as a normal quantized pass below) or, when there are no trees
        # to apply yet, a chunk-read-free gradstats pass over resident
        # pred/labels. One shared grid per (round, class) is what makes
        # every cross-chunk/cross-shard integer merge of the round
        # bit-exact.
        round_scales = None
        if quant:
            from ddt_tpu.ops.grad import GRAD_ROW_LIMIT, quant_scale_np

            if int(chunk_starts[-1]) >= GRAD_ROW_LIMIT:
                # The int32 overflow proof's row ceiling (ops/grad.py:
                # sum|q| <= 2^30 + n_rows must stay under INT32_MAX).
                raise ValueError(
                    f"quantized streaming over {int(chunk_starts[-1])} "
                    f"rows exceeds the overflow proof's row ceiling "
                    f"({GRAD_ROW_LIMIT}); use grad_dtype='f32'")
            acc = None
            if prev_trees is not None:
                data = chunks.get(0)
                for c in range(n_chunks):
                    tc0 = time.perf_counter()
                    pred_dev[c], st = backend.stream_round_start(
                        data, pred_dev[c], y_dev[c], prev_trees,
                        rnd=rnd, row_start=int(chunk_starts[c]))
                    if c + 1 < n_chunks:
                        data = chunks.get(c + 1)
                    part_rec.observe("roundstart", st, tc0)
                    acc = _merge_quant_stats(acc, np.asarray(st))
            else:
                for c in range(n_chunks):
                    acc = _merge_quant_stats(acc, np.asarray(
                        backend.stream_grad_stats(
                            pred_dev[c], y_dev[c], rnd=rnd,
                            row_start=int(chunk_starts[c]))))
            round_scales = [
                (quant_scale_np(acc[c_, 0], acc[c_, 1], cfg.grad_dtype),
                 quant_scale_np(acc[c_, 2], acc[c_, 3], cfg.grad_dtype))
                for c_ in range(C)]
            log.debug("streaming: round %d grad-quant scales %s", rnd,
                      round_scales)
            tele_counters.record_grad_quant_round()
        # Gradients for EVERY class tree of a round come from the
        # round-start preds (the Driver computes grad_hess once per round,
        # then grows C trees from its columns) — so pred updates are
        # deferred to the fused round-start pass.
        round_trees = []
        for cls in range(C):
            fmask = None
            if cfg.colsample_bytree < 1.0:
                from ddt_tpu.ops.sampling import colsample_mask

                fmask = colsample_mask(cfg.seed, rnd, cls,
                                       ens.n_features,
                                       cfg.colsample_bytree)
            feature = np.full(cfg.n_nodes_total, -1, np.int32)
            threshold_bin = np.zeros(cfg.n_nodes_total, np.int32)
            is_leaf = np.zeros(cfg.n_nodes_total, bool)
            leaf_value = np.zeros(cfg.n_nodes_total, np.float32)
            split_gain = np.zeros(cfg.n_nodes_total, np.float32)
            default_left = np.zeros(cfg.n_nodes_total, bool)
            tree = (feature, threshold_bin, is_leaf, default_left)

            sc = round_scales[cls] if quant else None
            prev_hist = None
            for depth in range(cfg.max_depth):
                sub = subtract and depth >= 1 and prev_hist is not None
                hist = None
                with ph("hist"):
                    if (depth == 0 and cls == 0 and prev_trees is not None
                            and not quant):
                        # Fused round-start: apply the previous round's
                        # trees to the resident preds AND build this
                        # tree's depth-0 histogram (the NEW round's
                        # bagging mask) in one dispatch per chunk.
                        # (Quantized rounds consumed this pass for
                        # scale stats above — depth 0 streams normally.)
                        data = chunks.get(0)
                        for c in range(n_chunks):
                            tc0 = time.perf_counter()
                            pred_dev[c], out = backend.stream_round_start(
                                data, pred_dev[c], y_dev[c], prev_trees,
                                rnd=rnd, row_start=int(chunk_starts[c]))
                            if c + 1 < n_chunks:
                                data = chunks.get(c + 1)
                            part_rec.observe("roundstart", out, tc0)
                            part = np.asarray(out)
                            if part.shape[1] != ens.n_features:
                                part = part[:, :ens.n_features]
                            hist = part if hist is None else hist + part
                    else:
                        # Sibling subtraction (levels >= 1): stream only
                        # LEFT-child histograms — half the per-chunk
                        # device work and half the collective payload.
                        for part in passes(tree, depth, "hist", cls, rnd,
                                           build_left=sub, scales=sc):
                            hist = part if hist is None else hist + part
                if sub:
                    hist = _assemble_subtracted_level(prev_hist, hist,
                                                      is_leaf, depth)
                # Quantized levels accumulate int32 — cross-chunk adds
                # and the subtraction above are EXACT; dequantize once
                # per level, feeding the shared split-decision home.
                histf = hist
                if quant:
                    histf = hist.astype(np.float32) * np.array(
                        [sc[0], sc[1]], np.float32)
                with ph("gain"):
                    _apply_level_splits(histf, cfg, depth, feature,
                                        threshold_bin, is_leaf, leaf_value,
                                        split_gain, default_left,
                                        feature_mask=fmask)
                prev_hist = hist if subtract else None

            # Final level: streamed (G, H) aggregates (int32 under
            # quantized gradients — dequantized after the last chunk).
            GH = None
            with ph("leaf"):
                for part in passes(tree, cfg.max_depth, "leaf", cls, rnd,
                                   scales=sc):
                    GH = part if GH is None else GH + part
                if quant:
                    _apply_final_leaves(
                        GH[:, 0].astype(np.float32) * np.float32(sc[0]),
                        GH[:, 1].astype(np.float32) * np.float32(sc[1]),
                        cfg, is_leaf, leaf_value)
                else:
                    _apply_final_leaves(GH[:, 0], GH[:, 1], cfg, is_leaf,
                                        leaf_value)

            round_trees.append(
                (feature, threshold_bin, is_leaf, leaf_value,
                 default_left))
            ens.feature[t_out] = feature
            ens.threshold_bin[t_out] = threshold_bin
            ens.is_leaf[t_out] = is_leaf
            ens.leaf_value[t_out] = leaf_value
            ens.split_gain[t_out] = split_gain
            if ens.default_left is not None:
                ens.default_left[t_out] = default_left
            t_out += 1

        prev_trees = round_trees
        if coll_bytes_round:
            tele_counters.record_collective(coll_bytes_round)
        tele_counters.record_grad_stream(
            C * tele_counters.grad_stream_bytes(
                int(chunk_starts[-1]), cfg.max_depth, cfg.grad_dtype))

        stop = False
        if ev is not None:
            # Two phases, matching the host loop's naming: "predict"
            # applies the round's trees to the resident val preds and
            # drains the raw scores (device work — the stream_update op
            # carries its XLA cost analysis under this name), "eval" is
            # the host-side (f64) metric reduction.
            with ph("predict"):
                scores = []
                data = val_chunks.get(0)
                for c in range(ev.n):
                    for cls, tree_full in enumerate(round_trees):
                        val_pred[c] = backend.stream_update_pred(
                            data, val_pred[c], tree_full, cfg.max_depth,
                            cls)
                    if c + 1 < ev.n:
                        data = val_chunks.get(c + 1)
                    scores.append(np.asarray(val_pred[c])[: ev.lens[c]])
            with ph("eval"):
                stop = ev.record(rnd, np.concatenate(scores))
        dt_ms = (time.perf_counter() - t_round) * 1e3
        _emit_round(run_log, rnd, dt_ms, ev, status=status)
        if window is not None:                # xprof window: stop edge
            window.round_end(rnd)
        if watchdog is not None:
            from ddt_tpu.robustness.watchdog import feed_watchdog

            feed_watchdog(watchdog, run_log, rnd,
                          part_rec.flush_round(rnd), log,
                          prefix="streaming: ")
        else:
            part_rec.flush_round(rnd)
        if stop:
            log.info(
                "streaming: early stop at round %d (best %s=%.6f at "
                "round %d)", rnd + 1, ev.metric, ev.best_score,
                ev.best_round + 1)
            emit_early_stop(run_log, rnd + 1, ev.metric,
                            ev.best_round + 1, ev.best_score)
            ens = ens.truncate((ev.best_round + 1) * C)
            checkpoint.maybe_save(checkpoint_dir, ens, cfg,
                                  ev.best_round + 1)
            return ens

        log.info("streaming: round %d/%d done", rnd + 1, cfg.n_trees)
        checkpoint.maybe_save(checkpoint_dir, ens, cfg, rnd + 1,
                              checkpoint_every)
        if checkpoint_every >= 1 and (rnd + 1) % checkpoint_every == 0:
            if status is not None and checkpoint_dir is not None:
                status.checkpoint_saved(rnd + 1)
            emit_train_heartbeat(
                run_log, rnd=rnd, total_rounds=cfg.n_trees,
                checkpoint_round=(rnd + 1 if checkpoint_dir is not None
                                  else None),
                ms_per_round=dt_ms)
        if (watchdog is not None and cfg.straggler_repartition
                and watchdog.pending_repartition
                and checkpoint_every >= 1
                and (rnd + 1) % checkpoint_every == 0
                and getattr(backend, "rotate_row_partitions", None)
                is not None):
            # The watchdog's streamed ACTION (the in-memory path's
            # rotate_row_partitions contract extended to the streamed
            # loop, ROADMAP item 2): rotate the row-shard -> device
            # assignment at the checkpoint boundary, move every
            # RESIDENT handle (labels, predictions) onto the rotated
            # mesh, drop the device chunk caches (their placements are
            # stale; the next pass re-uploads onto the new order), and
            # rotate a host-sharded source's chunk-shard -> host
            # assignment so reads keep following the devices. Shard
            # CONTENTS and the global row order are untouched — the
            # model is bit-identical by construction (tested). Scope
            # honesty: rotate_row_partitions is single-controller only
            # (multi-process meshes return False -> detection only,
            # like the in-memory path), and on one process the
            # assignment rotation is an identity (every slot is
            # local) — the rot() call keeps the mesh/ingest pairing
            # explicit for ROADMAP item 5's multi-process rework,
            # where host-level rotation makes both halves real.
            if backend.rotate_row_partitions():
                extra = 1 if C > 1 else 0
                y_dev = [type(h)(backend.reshard_rows(h.y),
                                 backend.reshard_rows(h.valid))
                         for h in y_dev]
                pred_dev = [backend.reshard_rows(p, extra_dims=extra)
                            for p in pred_dev]
                if ev is not None:
                    val_y_dev = [type(h)(backend.reshard_rows(h.y),
                                         backend.reshard_rows(h.valid))
                                 for h in val_y_dev]
                    val_pred = [backend.reshard_rows(p, extra_dims=extra)
                                for p in val_pred]
                chunks.clear()
                if val_chunks is not None:
                    val_chunks.clear()
                rot = getattr(chunk_fn, "rotate_assignment", None)
                if rot is not None:
                    rot()
                log.warning(
                    "streaming: repartitioned at round %d: rotated row "
                    "shards off the straggling device", rnd + 1)
                if run_log is not None:
                    run_log.emit("fault", kind="repartition",
                                 round=rnd + 1, rotation=1)
            watchdog.repartition_done()

    checkpoint.maybe_save(checkpoint_dir, ens, cfg, cfg.n_trees)
    return ens


def predict_streaming(
    chunk_fn: ChunkFn,
    n_chunks: int,
    ens: TreeEnsemble,
    backend=None,
    raw: bool = True,
    sink=None,
    max_in_flight: int = 3,
) -> "np.ndarray | int":
    """Out-of-core batch scoring: stream binned chunks through a
    DOUBLE-BUFFERED host→device pipeline; returns the concatenated scores
    ([R] or [R, C] raw margins; `raw=False` applies the loss's
    probability transform) — or, with a `sink`, streams them out too.

    The pipeline shape (device backends): chunk c's scoring program is
    dispatched asynchronously, chunk c+1's host read + H2D upload is
    enqueued WHILE c computes, and c's device→host score fetch is started
    (`copy_to_host_async`) as soon as its dispatch returns — so the H2D
    link, the traversal kernels, and the D2H drain all run concurrently
    (the round-5 overlapped-fetch result, extended to out-of-core input).
    The ensemble's pushed-down tables upload ONCE via the backend's
    compiled-ensemble cache and stay resident across chunks AND calls.
    Chunks may differ in size (each distinct size compiles one program —
    keep the number of distinct sizes small). Host backends (or
    backend=None) fall back to per-chunk scoring, same contract.

    `sink(chunk_idx, scores)` — when given, per-chunk scores stream out
    through it (at most `max_in_flight` chunks of scores are ever
    host-resident) and the TOTAL ROW COUNT is returned instead of an
    array: a 10B-row score vector has no business being concatenated in
    host memory (the CLI's --stream-dir predict writes per-shard .npy
    files through this).

    `chunk_fn` is the fit_streaming chunk source convention:
    (chunk_idx) -> (Xb_chunk uint8 [r, F], labels) — labels are ignored
    here, so score-time sources may return anything (e.g. None) there.
    Composes with distribution: each chunk row-shards over the backend's
    mesh like any other upload (multi-chip scoring from the same flag).
    """
    if n_chunks < 1:
        raise ValueError("predict_streaming needs n_chunks >= 1")

    def transform(out_np):
        if raw:
            return out_np
        from ddt_tpu.ops.predict import predict_proba
        import jax.numpy as jnp

        return np.asarray(predict_proba(jnp.asarray(out_np), ens.loss))

    rows = 0
    collected: list = []

    def emit(c, scores):
        nonlocal rows
        scores = transform(scores)
        rows += len(scores)
        if sink is None:
            collected.append(scores)
        else:
            sink(c, scores)

    if getattr(backend, "_predict_fn", None) is None:
        # Host path: no pipeline to overlap — score chunk by chunk
        # (through the backend's scorer when one was given: CPUDevice
        # prefers the native C++ traversal, bitwise-equal to NumPy).
        for c in range(n_chunks):
            Xc = np.asarray(chunk_fn(c)[0])
            emit(c, backend.predict_raw(ens, Xc) if backend is not None
                 else ens.predict_raw(Xc, binned=True))
    else:
        fn, ens_dev = backend._predict_fn(ens)   # compiled-ensemble cache
        # Device working-set bound: a chunk past the backend's per-call
        # row limit may NOT go down as one dispatch (the 10M x 1000
        # config OOM-kills the chip that way — backends/tpu.py
        # PREDICT_ROW_CHUNK). Oversized chunks route through
        # backend.predict_raw, whose internal chunking + overlapped
        # fetch already handle the big-batch case; the double-buffered
        # pipeline below covers the (normal) bounded-chunk regime.
        limit = (getattr(backend, "PREDICT_ROW_CHUNK", None) or 0) \
            * max(1, getattr(backend, "row_shards", 1))
        def fits(x):
            return not limit or x.shape[0] <= limit

        Xc = np.asarray(chunk_fn(0)[0])
        data = backend._put_rows(Xc, extra_dims=1) if fits(Xc) else None
        pending: list = []                       # (idx, device scores, n)

        def drain(keep: int) -> None:
            # Copies are already in flight; asarray only materialises.
            while len(pending) > keep:
                ci, o, n = pending.pop(0)
                emit(ci, np.asarray(o)[:n])  # ddtlint: disable=host-sync

        for c in range(n_chunks):
            cur, n_rows = Xc, Xc.shape[0]
            out_c = None if data is None else fn(*ens_dev, data)
            if c + 1 < n_chunks:                 # overlap next H2D
                Xc = np.asarray(chunk_fn(c + 1)[0])
                data = (backend._put_rows(Xc, extra_dims=1)
                        if fits(Xc) else None)
            if out_c is None:
                # Oversized chunk: drain the pipeline in order, then let
                # the backend's own chunked/overlapped path score it.
                drain(0)
                emit(c, backend.predict_raw(ens, cur))
                continue
            try:
                out_c.copy_to_host_async()       # start D2H drain now
            except AttributeError:               # non-jax backend arrays
                pass
            pending.append((c, out_c, n_rows))
            drain(max_in_flight)                 # bounded host residency
        drain(0)
    if sink is not None:
        return rows
    return np.concatenate(collected)


def _leaf_slot(Xb, feature, threshold_bin, is_leaf, max_depth,
               default_left=None, missing_bin_value=-1,
               cat_features=()) -> np.ndarray:
    """Heap slot where each row's traversal of one tree terminates."""
    R = Xb.shape[0]
    node = np.zeros(R, np.int64)
    for _ in range(max_depth):
        live = ~is_leaf[node]
        f = feature[node[live]]
        fv = Xb[live, f].astype(np.int64)
        go_right = _go_right(fv, node[live], feature, threshold_bin,
                             default_left, missing_bin_value, cat_features)
        node[live] = 2 * node[live] + 1 + go_right
    return node


def _rescore(ens: TreeEnsemble, n_trees_done: int, Xb, bs) -> np.ndarray:
    """Stateless pred of the first n_trees_done trees (cache_preds=False).
    [R] for binary/mse, [R, C] for softmax."""
    C = ens.n_classes if ens.loss == "softmax" else 1
    if n_trees_done == 0:
        if C > 1:
            return np.zeros((Xb.shape[0], C), np.float32)
        return np.full(Xb.shape[0], bs, np.float32)
    return ens.truncate(n_trees_done).predict_raw(
        Xb, binned=True).astype(np.float32)
