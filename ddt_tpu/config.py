"""Training configuration for the TPU-native distributed decision-tree trainer.

Capability contract: SURVEY.md §5 ("Config/flag system") — a single TrainConfig
dataclass mirrored by CLI flags, including the [BASELINE]-required backend flag
(FPGA vs TPU selectable by flag in the reference; here cpu/tpu, with fpga
present-but-stubbed so the flag surface matches).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


LOSSES = ("logloss", "mse", "softmax")
BACKENDS = ("cpu", "tpu", "fpga")  # fpga is a stub: flag parity with reference


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Hyper-parameters and system knobs for GBDT training.

    Mirrors the reference's flag set (depth, trees, bins, backend, partitions)
    as recovered in SURVEY.md §2 "CLI / config".

    Frozen: backend instances are cached keyed on config fields
    (backends/__init__.py), so a mutable config could desynchronize a cached
    backend from its cache key. Use .replace() to derive variants.
    """

    # --- model ---
    n_trees: int = 100          # boosting rounds (x n_classes trees for softmax)  # ddtlint: trace-inert — host-side loop bound only: every round traces the same program, and resume replays to the recorded round regardless of the target
    max_depth: int = 6          # levels of splits; complete heap tree layout
    n_bins: int = 255           # [BASELINE] "255 bins named explicitly"
    learning_rate: float = 0.1
    loss: str = "logloss"       # logloss | mse | softmax
    n_classes: int = 2          # used when loss == "softmax"

    # --- regularisation (XGBoost-style gain formula) ---
    reg_lambda: float = 1.0     # L2 on leaf weights
    min_child_weight: float = 1e-3   # min hessian sum per child
    min_split_gain: float = 0.0      # split only if gain > this

    # --- stochastic training (LightGBM/XGBoost-style bagging) ---
    subsample: float = 1.0           # row fraction per boosting round
    colsample_bytree: float = 1.0    # feature fraction per tree

    # --- missing values ---
    # "zero": NaN maps to bin 0 (v1 policy, no model change).
    # "learn": the TOP bin (n_bins-1) is reserved for NaN and every split
    #   learns a default direction for missing rows (left/right by gain),
    #   the standard histogram-GBDT treatment (LightGBM/XGBoost).
    missing_policy: str = "zero"

    # --- categorical features ---
    # Feature indices treated as CATEGORICAL (bin = category id from the
    # CategoricalEncoder): their split candidates are one-vs-rest
    # ("bin == k goes left") scored by one-hot gain, instead of ordinal
    # "bin <= t" — the Criteo-config treatment beyond frequency-ordinal
    # (SURVEY.md §2 "one-hot-gain variant"). Tuple (hashable: it keys
    # compiled programs). Categorical columns must be integer-coded
    # (never NaN).
    cat_features: tuple = ()

    # --- system ---
    backend: str = "tpu"        # cpu | tpu | fpga(stub)
    n_partitions: int = 1       # row partitions (data parallel over mesh axis)
    feature_partitions: int = 1  # column partitions (TP-analog mesh axis)
    # Declarative 2D mesh shape (Pr, Pf) — the ROADMAP item 2 spelling
    # of the (rows x features) layout (--mesh-shape Pr,Pf on the CLI).
    # When set it NORMALIZES into n_partitions/feature_partitions at
    # construction and then resets to None — a pure constructor-time
    # input, so both spellings of the same mesh produce byte-identical
    # configs (equal run-id digests, backend cache keys, checkpoint
    # fingerprints; `.replace()` never false-conflicts against a stale
    # stored pair). Setting it alongside a CONFLICTING explicit
    # n_partitions/feature_partitions raises — two sources of truth
    # for the mesh shape is a silent-wrong-mesh bug, not a
    # convenience.
    mesh_shape: "Optional[tuple]" = None  # ddtlint: trace-inert — describes the machine, not the model: the backend cache is process-local (one live mesh per process) and checkpoints must resume on a different topology
    host_partitions: int = 1    # cross-slice "hosts" mesh axis (DCN): row
    #   shards span hosts x rows; histogram psum phases ICI-first then DCN.
    #   Total devices used = host_partitions x n_partitions x
    #   feature_partitions.
    hist_impl: str = "auto"     # auto | matmul | segment | pallas
    # Sibling-subtraction trick in the level loop (ops/grow.
    # level_histograms): levels >= 1 build histograms only for LEFT
    # children and recover each right child as parent - left — half the
    # kernel work and half the allreduce payload per level. "auto"
    # enables it only on a real TPU chip (ops/grow.
    # resolve_hist_subtraction): right-child sums differ from a direct
    # build by f32 ULPs, which model quality never sees but the CPU
    # suites' streamed == in-memory BITWISE contracts would; "on"/"off"
    # force either side (tests use "on" with interpret-mode kernels).
    hist_subtraction: str = "auto"  # auto | on | off
    # Split-finding collective (parallel/comms.py, docs/PERF.md
    # "Histogram comms"). "allreduce": the classic full-histogram psum —
    # every device receives every feature's bins and runs the same
    # argmax. "reduce_scatter": each of the P row shards merges only its
    # F/P feature slab, finds its slab's best splits locally, and the
    # tiny per-shard winner tuples are all_gathered — per-level
    # collective payload drops from O(F·B) to O(F·B/P) + O(P·nodes).
    # "auto" picks reduce_scatter exactly when a row mesh is live (and
    # the feature axis is not separately sharded); trees are
    # structure-identical either way (comms.combine_shard_winners
    # reproduces the single-device argmax tie-break exactly).
    split_comms: str = "auto"   # auto | allreduce | reduce_scatter
    # Wire dtype of the histogram collective (parallel/comms.py
    # hist_reduce; NEVER on by default): "bf16" halves payload bytes at
    # ~2^-9 relative rounding per partial; "int32_fixed" reduces on a
    # shared fixed-point grid with an INTEGER sum — order-independent,
    # so N-partition merges are bit-stable where f32 psum order was not.
    # Both carry a computed error bound (comms.comms_error_bound) held
    # by the split-agreement contract tests.
    hist_comms_dtype: str = "f32"   # f32 | bf16 | int32_fixed
    # Slab-pipelined comms overlap: split each level's histogram
    # build + collective into N feature slabs so slab k+1's histogram
    # kernels dispatch while slab k's collective is still on the wire
    # (XLA's async collectives hide DCN latency behind VPU work).
    # f32/bf16 collectives are elementwise, so slab phasing is
    # bit-identical to the monolithic form by construction (tested);
    # int32_fixed computes its fixed-point scale per collective, so
    # each SLAB quantizes on its own (tighter) grid — deterministic,
    # within the same error bound, but the slab count is part of that
    # mode's numerics (split agreement still holds; tested). 0 =
    # auto: pipelined only on a real TPU mesh (where a wire exists to
    # hide); 1 = off; N >= 2 forces N slabs (tests).
    hist_comms_slabs: int = 0   # 0 = auto | 1 = off | N slabs
    # Batch-scoring traversal implementation (ops/predict.py dispatch):
    # "auto" takes the Pallas VMEM traversal kernel on binned data when a
    # real TPU backs the computation and the shape fits its VMEM budget,
    # falling back to the one-hot compare+reduce path; "pallas"/"onehot"
    # force one side (pallas off-TPU runs the interpreter — tests only).
    # "lut" is the TreeLUT-style int8 quantized traversal
    # (ops/predict_lut.py — the low-latency serving opt-in, `--quantized`
    # on the CLI): int8 thresholds + fp16 leaf tables, ~4x less HBM
    # traffic per request, leaf values within the tables' documented
    # max-abs-error bound of f32; auto-falls back to the f32 path when
    # the shape exceeds the kernel's VMEM budget (predict_lut_fits).
    # "lut4" is the bit-packed int4 tier (`--quantized int4`): leaf
    # tables two-nibbles-per-byte with per-tree scales (thresholds join
    # the pack on <= 15-bin models), halving the int8 tier's resident
    # bytes again; falls back int4 -> int8 -> f32 down the same guard
    # ladder (predict_lut4_fits / predict_lut_fits).
    predict_impl: str = "auto"  # auto | pallas | onehot | lut | lut4
    seed: int = 0
    # Cap on boosting rounds per fused device dispatch (Driver._fit_fused).
    # One block already amortizes dispatch latency to nothing, so bigger
    # buys no throughput — but an UNBOUNDED block turns long configs into
    # one multi-minute device program with zero host interaction, which
    # (a) remote-attached runtimes can kill as hung (the full 500-round
    # depth-8 Covertype config crashed the round-4 chip worker as a single
    # ~15-minute dispatch; 100-round blocks run it fine) and (b) starves
    # checkpoint and progress-log cadence. The default's ~1-2 device-
    # minutes-per-block headroom is deployment-specific — deeper/wider
    # configs on watchdogged runtimes tune it DOWN (--fused-block-rounds).
    fused_block_rounds: int = 100

    # --- numerics ---
    # Histogram accumulators are always float32 (preferred_element_type on the
    # MXU); this knob controls the one-hot matmul INPUT dtype — bfloat16 rides
    # the systolic array at full rate, float32 forces exact accumulation.
    matmul_input_dtype: str = "bfloat16"
    # Quantized-gradient training (ops/grad.py; docs/PERF.md "Quantized
    # gradients"; NEVER on by default): "int8"/"int16" discretize g/h
    # once per (tree, output dim) onto a shared power-of-two grid —
    # per-dim scale from psum'd max|g|/sum|g|, SEEDED stochastic
    # rounding (unbiased, chaos-replayable: a pure function of (seed,
    # tree, global row), never per retry attempt) — and the whole
    # histogram pipeline then runs INTEGER: int32 VMEM accumulation,
    # exact sibling subtraction (hist_subtraction 'auto' resolves ON
    # everywhere — the f32-ULP caveat is gone), bit-stable int32
    # cross-shard/chunk merges, one dequantize after the last merge.
    # Cuts the per-level g/h HBM stream 4x (int8) / 2x (int16) and
    # halves every level >= 1's collective payload on platforms where
    # f32 subtraction was gated off. Split gains come from dequantized
    # totals with a computed worst-case bound
    # (ops/grad.grad_quant_error_bound — witnessed, not hoped).
    # Composes with every mesh/streaming path EXCEPT the host-backend
    # streaming loop (refused loudly) and the CPU oracle backend.
    grad_dtype: str = "f32"     # f32 | int16 | int8

    # --- robustness (docs/ROBUSTNESS.md) ---
    # Path to a JSON fault-injection plan (robustness/faultplan.py); the
    # chaos harness. None (the default) compiles every injection seam to
    # a single module-global read — the telemetry disabled-path bar.
    fault_plan: Optional[str] = None  # ddtlint: trace-inert — chaos-harness knob: injected faults must be invisible to config identity so an injected run's checkpoints resume clean
    # Act on the straggler watchdog: when the flight recorder's
    # per-round partition attribution shows one device persistently past
    # the skew threshold, rotate the row-shard -> device assignment at
    # the next checkpoint boundary (shard contents untouched — the model
    # is unchanged by construction). Detection events are always emitted
    # on telemetry mesh runs; this flag gates the ACTION, and it forces
    # the granular Driver path (repartitioning needs round-boundary
    # control a fused block does not yield).
    straggler_repartition: bool = False  # ddtlint: trace-inert — host-side scheduling action (shard->device rotation); the model is unchanged by construction, so no contract may key on it
    # Watchdog trip point: a device whose per-round phase total exceeds
    # the MEDIAN OF THE OTHER lanes by this factor is a straggler
    # candidate (excluding the candidate keeps the default meaningful
    # even on a 2-lane mesh — robustness/watchdog.py).
    straggler_skew_threshold: float = 2.0  # ddtlint: trace-inert — watchdog trip point on the detection side only; never read inside a trace and never shapes the trained model

    def __post_init__(self) -> None:
        if self.loss not in LOSSES:
            raise ValueError(f"loss must be one of {LOSSES}, got {self.loss!r}")
        if self.backend not in BACKENDS:
            raise ValueError(
                f"backend must be one of {BACKENDS}, got {self.backend!r}"
            )
        if not (1 <= self.n_bins <= 256):
            raise ValueError("n_bins must be in [1, 256] (uint8 binned data)")
        if self.max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        if self.loss == "softmax" and self.n_classes < 2:
            raise ValueError("softmax needs n_classes >= 2")
        if self.mesh_shape is not None:
            ms = tuple(int(v) for v in self.mesh_shape)
            if len(ms) != 2 or any(v < 1 for v in ms):
                raise ValueError(
                    f"mesh_shape must be a (Pr >= 1, Pf >= 1) pair, got "
                    f"{self.mesh_shape!r}")
            pr, pf = ms
            if self.n_partitions not in (1, pr):
                raise ValueError(
                    f"mesh_shape={ms} conflicts with n_partitions="
                    f"{self.n_partitions}; set one, not both")
            if self.feature_partitions not in (1, pf):
                raise ValueError(
                    f"mesh_shape={ms} conflicts with feature_partitions="
                    f"{self.feature_partitions}; set one, not both")
            object.__setattr__(self, "n_partitions", pr)
            object.__setattr__(self, "feature_partitions", pf)
        # CANONICALIZE to None after normalizing: mesh_shape is a pure
        # constructor-time input, so both spellings of the same mesh
        # produce byte-IDENTICAL configs (equal run-id digests, backend
        # cache keys, checkpoint fingerprints) and `.replace(
        # n_partitions=...)` on a mesh_shape-built config cannot
        # false-conflict against a stale stored pair. Consumers read
        # the normalized n_partitions/feature_partitions fields.
        object.__setattr__(self, "mesh_shape", None)
        if (self.n_partitions < 1 or self.feature_partitions < 1
                or self.host_partitions < 1):
            raise ValueError("partition counts must be >= 1")
        if self.fused_block_rounds < 1:
            raise ValueError(
                f"fused_block_rounds must be >= 1, got "
                f"{self.fused_block_rounds}")
        if not (0.0 < self.subsample <= 1.0):
            raise ValueError("subsample must be in (0, 1]")
        if not (0.0 < self.colsample_bytree <= 1.0):
            raise ValueError("colsample_bytree must be in (0, 1]")
        if self.hist_subtraction not in ("auto", "on", "off"):
            raise ValueError(
                f"hist_subtraction must be auto|on|off, got "
                f"{self.hist_subtraction!r}"
            )
        if self.split_comms not in ("auto", "allreduce", "reduce_scatter"):
            raise ValueError(
                f"split_comms must be auto|allreduce|reduce_scatter, got "
                f"{self.split_comms!r}"
            )
        if self.hist_comms_dtype not in ("f32", "bf16", "int32_fixed"):
            raise ValueError(
                f"hist_comms_dtype must be f32|bf16|int32_fixed, got "
                f"{self.hist_comms_dtype!r}"
            )
        if self.hist_comms_slabs < 0:
            raise ValueError(
                f"hist_comms_slabs must be >= 0 (0 = auto), got "
                f"{self.hist_comms_slabs}"
            )
        if self.grad_dtype not in ("f32", "int16", "int8"):
            raise ValueError(
                f"grad_dtype must be f32|int16|int8, got "
                f"{self.grad_dtype!r}"
            )
        if self.grad_dtype != "f32" and self.hist_comms_dtype != "f32":
            # Refuse-loudly (ISSUE 14): quantized-gradient histograms are
            # ALREADY integer partials on one shared grid — compressing
            # the collective on top (bf16 rounding or int32_fixed's
            # per-collective re-quantize) would DOUBLE-quantize, voiding
            # the grad_quant error bound while buying nothing (the
            # integer merge is bit-stable without help). Same guard at
            # the wire in parallel/comms.hist_reduce.
            raise ValueError(
                f"grad_dtype={self.grad_dtype!r} with hist_comms_dtype="
                f"{self.hist_comms_dtype!r} would double-quantize the "
                "histogram collective: quantized-gradient partials are "
                "integer values on one shared grid and merge bit-stably "
                "as-is; keep hist_comms_dtype='f32'"
            )
        if self.predict_impl not in ("auto", "pallas", "onehot", "lut",
                                     "lut4"):
            raise ValueError(
                f"predict_impl must be auto|pallas|onehot|lut|lut4, got "
                f"{self.predict_impl!r}"
            )
        if self.missing_policy not in ("zero", "learn"):
            raise ValueError(
                f"missing_policy must be zero|learn, got "
                f"{self.missing_policy!r}"
            )
        if self.missing_policy == "learn" and self.n_bins < 3:
            raise ValueError(
                "missing_policy='learn' reserves the top bin; n_bins >= 3"
            )
        if self.straggler_skew_threshold <= 1.0:
            raise ValueError(
                "straggler_skew_threshold must be > 1.0 (1.0 is a "
                f"perfectly balanced mesh), got "
                f"{self.straggler_skew_threshold}"
            )
        # Normalize unconditionally: a list (even an empty one) must
        # become a tuple or the backend cache key is unhashable.
        object.__setattr__(
            self, "cat_features",
            tuple(sorted(int(f) for f in self.cat_features)))
        if self.cat_features:
            if self.cat_features[0] < 0:
                raise ValueError("cat_features indices must be >= 0")
            if self.missing_policy == "learn":
                raise ValueError(
                    "cat_features with missing_policy='learn' is not "
                    "supported: the reserved NaN bin would silently merge "
                    "the encoder's top category id into its neighbor "
                    "(categorical columns are integer-coded and never "
                    "NaN, so use missing_policy='zero')"
                )

    @property
    def n_nodes_total(self) -> int:
        """Heap-layout node count for a complete tree of `max_depth` levels."""
        return 2 ** (self.max_depth + 1) - 1

    @property
    def n_leaves_max(self) -> int:
        return 2 ** self.max_depth

    @property
    def missing_bin_value(self) -> int:
        """Bin index reserved for NaN rows under missing_policy='learn',
        -1 otherwise (the single home of the reserved-bin convention —
        every routing/traversal site reads this)."""
        return self.n_bins - 1 if self.missing_policy == "learn" else -1

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)

    @classmethod
    def from_file(cls, path: str) -> "TrainConfig":
        """TrainConfig from a YAML or JSON file (SURVEY.md §5 "Config/flag
        system": the optional file form of the flag set). Unknown keys
        fail loudly — a typo'd hyperparameter silently training with its
        default is worse than an error."""
        return cls(**load_config_file(path))


def load_config_file(path: str) -> dict:
    """Dict of TrainConfig fields from a .yaml/.yml/.json file, key-
    validated. The CLI overlays these onto flag-built configs (file wins
    for the fields it names)."""
    import json

    with open(path) as f:
        if path.endswith((".yaml", ".yml")):
            import yaml

            d = yaml.safe_load(f)
        else:
            d = json.load(f)
    if not isinstance(d, dict):
        raise ValueError(f"{path} must contain a mapping, got {type(d)}")
    fields = {f.name for f in dataclasses.fields(TrainConfig)}
    unknown = sorted(set(d) - fields)
    if unknown:
        raise ValueError(
            f"{path} has unknown TrainConfig keys {unknown}; "
            f"valid: {sorted(fields)}"
        )
    if "cat_features" in d:
        d["cat_features"] = tuple(d["cat_features"])
    if d.get("mesh_shape") is not None:
        d["mesh_shape"] = tuple(d["mesh_shape"])
    return d
