"""scikit-learn-style estimator facade over the L8 train/predict API.

`DDTClassifier` / `DDTRegressor` wrap quantization + training + scoring in
the fit/predict idiom so the framework drops into sklearn-shaped pipelines
(the reference exposes a train/predict CLI; this is the adoption-surface
equivalent for Python users). Not a full sklearn BaseEstimator — no sklearn
dependency — but follows its conventions: constructor stores hyperparams
verbatim, fit() learns state on `self`, fitted attributes end in "_".
"""

from __future__ import annotations

import numpy as np

from ddt_tpu.config import TrainConfig


class _DDTBase:
    _LOSS: str = ""

    def __init__(
        self,
        n_trees: int = 100,
        max_depth: int = 6,
        n_bins: int = 255,
        learning_rate: float = 0.1,
        reg_lambda: float = 1.0,
        min_child_weight: float = 1e-3,
        min_split_gain: float = 0.0,
        subsample: float = 1.0,
        colsample_bytree: float = 1.0,
        backend: str = "tpu",
        n_partitions: int = 1,
        seed: int = 0,
        missing_policy: str = "zero",
        cat_features: tuple = (),
    ):
        self.n_trees = n_trees
        self.max_depth = max_depth
        self.n_bins = n_bins
        self.learning_rate = learning_rate
        self.reg_lambda = reg_lambda
        self.min_child_weight = min_child_weight
        self.min_split_gain = min_split_gain
        self.subsample = subsample
        self.colsample_bytree = colsample_bytree
        self.backend = backend
        self.n_partitions = n_partitions
        self.seed = seed
        self.missing_policy = missing_policy
        self.cat_features = cat_features

    @classmethod
    def _param_names(cls) -> tuple:
        """Constructor arg names, derived from the signature (sklearn's own
        approach) so the list cannot drift from __init__."""
        import inspect

        return tuple(inspect.signature(cls.__init__).parameters)[1:]

    def get_params(self, deep: bool = True) -> dict:
        """Constructor params (sklearn clone/GridSearchCV protocol)."""
        return {k: getattr(self, k) for k in self._param_names()}

    def set_params(self, **params):
        names = self._param_names()
        for k, v in params.items():
            if k not in names:
                raise ValueError(
                    f"unknown parameter {k!r}; valid: {names}")
            setattr(self, k, v)
        return self

    def _cfg(self, **extra) -> TrainConfig:
        extra.setdefault("loss", self._LOSS)
        return TrainConfig(
            n_trees=self.n_trees,
            max_depth=self.max_depth,
            n_bins=self.n_bins,
            learning_rate=self.learning_rate,
            reg_lambda=self.reg_lambda,
            min_child_weight=self.min_child_weight,
            min_split_gain=self.min_split_gain,
            subsample=self.subsample,
            colsample_bytree=self.colsample_bytree,
            backend=self.backend,
            n_partitions=self.n_partitions,
            seed=self.seed,
            missing_policy=self.missing_policy,
            cat_features=tuple(self.cat_features),
            **extra,
        )

    def fit(self, X, y, sample_weight=None, *, eval_set=None,
            eval_metric=None, early_stopping_rounds=None, run_log=None):
        from ddt_tpu import api

        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        cfg = self._cfg(**self._fit_cfg_extra(y))
        if eval_set is not None:
            eval_set = (np.asarray(eval_set[0], np.float32),
                        np.asarray(eval_set[1]))
        # early_stopping_rounds passes through even without an eval_set so
        # the Driver's "requires an eval_set" error reaches the user.
        # run_log: the telemetry JSONL stream (path or telemetry.RunLog;
        # docs/OBSERVABILITY.md).
        res = api.train(X, y, cfg, log_every=10 ** 9, eval_set=eval_set,
                        eval_metric=eval_metric,
                        early_stopping_rounds=early_stopping_rounds,
                        sample_weight=sample_weight, run_log=run_log)
        self.ensemble_ = res.ensemble
        self.mapper_ = res.mapper
        self.n_features_in_ = X.shape[1]
        self.feature_importances_ = self.ensemble_.feature_importances()
        # sklearn/LightGBM-convention eval attributes (None / {} when no
        # eval_set was given).
        self.best_iteration_ = res.best_round
        self.best_score_ = res.best_score
        self.evals_result_ = {}
        for rec in res.history:
            for k, v in rec.items():
                if k.startswith("valid_"):
                    self.evals_result_.setdefault(
                        k[len("valid_"):], []).append(v)
        return self

    def _fit_cfg_extra(self, y) -> dict:
        return {}

    def _raw(self, X) -> np.ndarray:
        from ddt_tpu import api

        # Score through the estimator's configured backend: device
        # backends serve repeat calls from the compiled-ensemble cache
        # (pushdown + upload paid once per fitted model — backends/tpu),
        # and CPUDevice's native traversal is bitwise-equal to the NumPy
        # scorer, so this routing changes no prediction.
        return api.predict(self.ensemble_, np.asarray(X, np.float32),
                           mapper=self.mapper_, raw=True, cfg=self._cfg())


class DDTClassifier(_DDTBase):
    """Gradient-boosted decision-tree classifier (binary or multiclass)."""

    _LOSS = "logloss"

    def _fit_cfg_extra(self, y) -> dict:
        n = len(np.unique(y))
        if n > 2:
            return {"loss": "softmax", "n_classes": n}
        return {}

    def fit(self, X, y, sample_weight=None, *, eval_set=None,
            eval_metric=None, early_stopping_rounds=None, run_log=None):
        y = np.asarray(y)
        classes = np.unique(y)
        if len(classes) < 2:
            # Matches sklearn: fail at fit time, not with an opaque
            # IndexError at predict time (classes_[argmax over 2 columns]).
            found = (f"only one class: {classes[0]!r}" if len(classes)
                     else "no samples")
            raise ValueError(
                "This solver needs samples of at least 2 classes in the "
                f"data, but the data contains {found}"
            )
        # Map labels to 0..C-1 for training; predictions map back.
        y_enc = np.searchsorted(classes, y)
        if eval_set is not None:
            yv = np.asarray(eval_set[1])
            unseen = ~np.isin(yv, classes)
            if unseen.any():
                raise ValueError(
                    f"eval_set contains labels not present in y: "
                    f"{np.unique(yv[unseen])!r}"
                )
            eval_set = (eval_set[0], np.searchsorted(classes, yv))
        super().fit(X, y_enc, eval_set=eval_set, eval_metric=eval_metric,
                    early_stopping_rounds=early_stopping_rounds,
                    sample_weight=sample_weight, run_log=run_log)
        self.classes_ = classes
        return self

    def predict_proba(self, X) -> np.ndarray:
        from ddt_tpu import api

        # The raw->probability transform lives in TreeEnsemble.predict
        # (api.predict raw=False); binary returns p(class 1), stacked here.
        # cfg routes through the backend's compiled-ensemble cache (_raw).
        p = api.predict(self.ensemble_, np.asarray(X, np.float32),
                        mapper=self.mapper_, cfg=self._cfg())
        if p.ndim == 2:            # softmax: already a distribution
            return p
        return np.stack([1.0 - p, p], axis=1)

    def predict(self, X) -> np.ndarray:
        return self.classes_[self.predict_proba(X).argmax(axis=1)]

    def score(self, X, y) -> float:
        """Mean accuracy."""
        return float((self.predict(X) == np.asarray(y)).mean())


class DDTRegressor(_DDTBase):
    """Gradient-boosted decision-tree regressor (squared error)."""

    _LOSS = "mse"

    def predict(self, X) -> np.ndarray:
        return self._raw(X)

    def score(self, X, y) -> float:
        """R^2 coefficient of determination."""
        y = np.asarray(y, np.float64)
        pred = self.predict(X).astype(np.float64)
        ss_res = float(np.square(y - pred).sum())
        ss_tot = float(np.square(y - y.mean()).sum())
        return 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0
