"""ddt_tpu: a TPU-native distributed decision-tree (GBDT) framework.

Brand-new JAX/XLA/Pallas realisation of the capabilities of
fpgasystems/Distributed-DecisionTrees (see SURVEY.md for the capability
contract; the reference source was unavailable — everything here is built to
BASELINE.json's north star, not translated).

Public surface (layer L8):
    from ddt_tpu import train, predict, TrainConfig, TreeEnsemble
    python -m ddt_tpu.cli train --backend=tpu
"""

from ddt_tpu.api import TrainResult, predict, train
from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble
from ddt_tpu.sklearn import DDTClassifier, DDTRegressor
from ddt_tpu.telemetry.events import RunLog

__version__ = "0.1.0"

__all__ = [
    "train",
    "predict",
    "TrainResult",
    "TrainConfig",
    "TreeEnsemble",
    "DDTClassifier",
    "DDTRegressor",
    "RunLog",
    "__version__",
]
